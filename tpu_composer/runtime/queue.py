"""Rate-limited, deduplicating work queue.

Reference analog: k8s.io/client-go/util/workqueue as used implicitly by every
controller-runtime reconciler in /root/reference/internal/controller. Contract:

- ``add(key)`` enqueues; a key already queued or being processed is not
  double-queued (dedup) but a key re-added while in-flight is re-queued when
  ``done`` is called (the "dirty" set);
- ``add_after(key, delay)`` schedules a delayed requeue (the reference's
  ``RequeueAfter: 30s`` results);
- ``add_rate_limited(key)`` applies per-key exponential backoff with
  decorrelated jitter (failures) — deterministic 2^n backoff made every key
  that failed during a fabric blackout requeue in the same instant when it
  healed (thundering herd into the just-recovered endpoint); jitter spreads
  the recovery wave while keeping the same expected growth. Jitter alone is
  not enough when an OUTAGE aligns the expiries: backoff entries that all
  came due during a blackout used to mass-promote in one ``_promote_ready``
  pass on heal, so promotion now re-spreads any such stale herd past
  ``herd_threshold`` over one ``herd_spread`` quantum;
- ``forget(key)`` resets the backoff (successful reconcile) AND lazily
  invalidates the key's pending backoff entries: a key that succeeded must
  not be woken again by a stale pre-success failure requeue. Plain
  ``add_after`` entries (periodic polls) are never invalidated — they are
  liveness, not backoff.

Causal tracing rides the queue: ``add`` called from inside a traced span
(a dispatcher completion latch, a reconcile that just submitted a fabric
op) captures a ``TraceContext`` handoff for the key — emitting the Chrome
flow-start on the producing thread — and the worker that dequeues the key
consumes it via ``pop_context``, so the next reconcile span joins the same
trace with a cross-thread flow arrow. Deduped re-adds keep the NEWEST
context (latest causality wins).

The ready queue is a ``collections.deque``: under deep queues (an attach
wave fanning hundreds of keys out) the old ``list.pop(0)`` made every get
O(n) — O(n^2) to drain the wave.
"""

from __future__ import annotations

import collections
import heapq
import random
import threading
import time
from typing import Deque, Dict, Hashable, List, Optional, Tuple

from tpu_composer.runtime import tracing
from tpu_composer.runtime.metrics import queue_wait_seconds


class RateLimitingQueue:
    def __init__(
        self,
        base_delay: float = 0.005,
        max_delay: float = 16.0,
        jitter: Optional[random.Random] = None,
        name: str = "queue",
        herd_threshold: int = 8,
        herd_spread: float = 1.0,
        herd_stale: float = 0.25,
    ) -> None:
        self._base_delay = base_delay
        self._max_delay = max_delay
        self._rng = jitter or random.Random()
        # Post-outage herd pacing (see _promote_ready): one promotion pass
        # finding more than herd_threshold backoff entries that ALL went
        # stale (ready more than herd_stale ago — the signature of backoffs
        # expiring during a blackout while the workers were wedged on the
        # dead store) promotes the first herd_threshold and re-spreads the
        # rest over U(0, herd_spread) so heal does not release the whole
        # herd in one instant. herd_spread <= 0 disables the pacing.
        self._herd_threshold = max(1, herd_threshold)
        self._herd_spread = herd_spread
        self._herd_stale = herd_stale
        #: Label for tpuc_queue_wait_seconds{queue}: controllers pass
        #: their name so saturation is attributable per queue.
        self.name = name
        # key -> last jittered delay (decorrelated jitter state)
        self._last_delay: Dict[Hashable, float] = {}
        self._cond = threading.Condition()
        self._queue: Deque[Hashable] = collections.deque()
        self._queued: set = set()
        self._processing: set = set()
        self._dirty: set = set()
        self._failures: Dict[Hashable, int] = {}
        # min-heap of (ready_time, seq, key, backoff_gen); backoff_gen is
        # None for plain add_after entries and the key's backoff generation
        # at push time for add_rate_limited entries — forget() bumps the
        # generation so stale backoff entries evaporate at promotion
        # instead of spuriously re-waking a key that already succeeded.
        self._delayed: List[Tuple[float, int, Hashable, Optional[int]]] = []
        self._backoff_gen: Dict[Hashable, int] = {}
        self._backoff_pending: Dict[Hashable, int] = {}  # outstanding entries
        # key -> TraceContext handed off by the most recent add() made from
        # inside a traced span; claimed at dequeue (get() moves it to
        # _claimed_ctx under the same lock hold) and consumed by the
        # worker's pop_context. Bounded by queued+dirty+processing counts.
        self._trace_ctx: Dict[Hashable, tracing.TraceContext] = {}
        self._claimed_ctx: Dict[Hashable, tracing.TraceContext] = {}
        # key -> monotonic time it became READY (enqueued, or promoted
        # from the delayed heap): the tpuc_queue_wait_seconds source.
        # Delayed entries are deliberately not timed from add_after — the
        # wait that signals saturation is ready-to-run sitting unclaimed,
        # not an intentional backoff/poll delay.
        self._enqueued_at: Dict[Hashable, float] = {}
        self._seq = 0
        self._shutdown = False

    # ------------------------------------------------------------------
    def add(
        self, key: Hashable, ctx: Optional[tracing.TraceContext] = None
    ) -> None:
        with self._cond:
            if self._shutdown:
                # No handoff either: a flow-start with no consumer would
                # leave a dangling arrow in the exported trace.
                return
            if ctx is None:
                active = tracing.context()
                if active is not None:
                    # Capture the causal edge NOW, on the producing thread
                    # — the flow-start must bind to the span doing the add.
                    # (tracing's ring lock nests inside this queue's lock;
                    # tracing never calls back into the queue.)
                    ctx = active.handoff()
            if ctx is not None:
                old = self._trace_ctx.get(key)
                if old is not None:
                    # Newest causality wins; close the superseded
                    # handoff's arrow into this producing span so no
                    # flow-start dangles unmatched in the export.
                    tracing.link(old)
                self._trace_ctx[key] = ctx
            if key in self._processing:
                self._dirty.add(key)
                return
            if key not in self._queued:
                self._queued.add(key)
                self._queue.append(key)
                self._enqueued_at.setdefault(key, time.monotonic())
                self._cond.notify()

    def pop_context(self, key: Hashable) -> Optional[tracing.TraceContext]:
        """Consume the propagated trace context for a just-dequeued key.
        Returns only the context CLAIMED by this key's dequeue (get() moves
        it out of the parked map under the same lock hold), so a context
        parked by a concurrent add() after the dequeue is preserved for
        the requeued reconcile it belongs to."""
        with self._cond:
            return self._claimed_ctx.pop(key, None)

    def add_after(self, key: Hashable, delay: float) -> None:
        if delay <= 0:
            self.add(key)
            return
        with self._cond:
            if self._shutdown:
                return
            self._push_delayed(key, delay, None)

    def add_rate_limited(self, key: Hashable) -> None:
        with self._cond:
            if self._shutdown:
                return
            self._failures[key] = self._failures.get(key, 0) + 1
            # Decorrelated jitter (the AWS formula): next ∈ U(base, 3·prev),
            # capped. Expected growth ≈ 1.5x/attempt — same shape as the old
            # 2^n curve, but two keys failing in lockstep drift apart
            # instead of hammering the store/fabric on synchronized beats.
            prev = self._last_delay.get(key, self._base_delay)
            delay = min(
                self._max_delay, self._rng.uniform(self._base_delay, prev * 3)
            )
            self._last_delay[key] = delay
            self._backoff_pending[key] = self._backoff_pending.get(key, 0) + 1
            self._push_delayed(key, delay, self._backoff_gen.get(key, 0))

    def _push_delayed(
        self, key: Hashable, delay: float, gen: Optional[int]
    ) -> None:
        # caller holds the lock
        self._seq += 1
        heapq.heappush(
            self._delayed, (time.monotonic() + delay, self._seq, key, gen)
        )
        self._cond.notify()

    def forget(self, key: Hashable) -> None:
        # NOTE: deliberately leaves _trace_ctx alone. forget() runs on the
        # success path while the key is still marked processing — its own
        # context was already consumed by pop_context at dequeue, so any
        # context present NOW was parked by a concurrent add() (a dispatcher
        # completion latch firing mid-reconcile, which also set the dirty
        # bit) and belongs to the upcoming requeued reconcile. Popping it
        # here would sever the completion -> requeue flow arrow.
        with self._cond:
            self._failures.pop(key, None)
            self._last_delay.pop(key, None)
            if self._backoff_pending.get(key):
                # Outstanding backoff entries become stale: bump the
                # generation so _promote_ready drops them on arrival. The
                # per-key state is pruned when the last stale entry drains
                # (bounded by the backoff cap), so churning keys don't
                # accrete bookkeeping.
                self._backoff_gen[key] = self._backoff_gen.get(key, 0) + 1

    def retries(self, key: Hashable) -> int:
        with self._cond:
            return self._failures.get(key, 0)

    # ------------------------------------------------------------------
    def _promote_ready(self, now: float) -> None:
        # caller holds the lock
        stale_promoted = 0
        while self._delayed and self._delayed[0][0] <= now:
            ready_t, _, key, gen = heapq.heappop(self._delayed)
            if (
                gen is not None
                and self._herd_spread > 0
                and now - ready_t > self._herd_stale
            ):
                # Backoff entry that expired a while ago — the workers
                # were not draining when it came due (store blackout, a
                # long stall). If a whole herd of them arrives in THIS
                # pass, promote only the first herd_threshold and
                # re-spread the rest with fresh jittered ready times:
                # per-key decorrelated jitter spreads failures in time,
                # but a blackout ALIGNS the expiries and heal would
                # otherwise release them all in the same instant.
                stale_promoted += 1
                if stale_promoted > self._herd_threshold:
                    self._seq += 1
                    heapq.heappush(self._delayed, (
                        now + self._rng.uniform(0.0, self._herd_spread),
                        self._seq, key, gen,
                    ))
                    continue
            if gen is not None:
                current = self._backoff_gen.get(key, 0)
                left = self._backoff_pending.get(key, 1) - 1
                if left > 0:
                    self._backoff_pending[key] = left
                else:
                    # Last outstanding entry drained — prune the per-key
                    # bookkeeping (next backoff starts back at gen 0).
                    self._backoff_pending.pop(key, None)
                    self._backoff_gen.pop(key, None)
                if gen != current:
                    continue  # forgotten since scheduling — stale backoff
            if key in self._processing:
                self._dirty.add(key)
            elif key not in self._queued:
                self._queued.add(key)
                self._queue.append(key)
                self._enqueued_at.setdefault(key, now)

    def get(self, timeout: Optional[float] = None) -> Optional[Hashable]:
        """Block until a key is ready (or timeout/shutdown → None)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                now = time.monotonic()
                self._promote_ready(now)
                if self._queue:
                    key = self._queue.popleft()
                    self._queued.discard(key)
                    self._processing.add(key)
                    enq = self._enqueued_at.pop(key, None)
                    if enq is not None:
                        queue_wait_seconds.observe(
                            max(0.0, now - enq), queue=self.name
                        )
                    # Claim the key's parked context ATOMICALLY with the
                    # dequeue: an add() landing after this point (e.g. a
                    # completion latch) parks a context for the NEXT
                    # reconcile — pop_context must never hand it to the
                    # one that just started.
                    if key in self._trace_ctx:
                        self._claimed_ctx[key] = self._trace_ctx.pop(key)
                    return key
                if self._shutdown:
                    return None
                waits = []
                if self._delayed:
                    waits.append(self._delayed[0][0] - now)
                if deadline is not None:
                    if deadline <= now:
                        return None
                    waits.append(deadline - now)
                self._cond.wait(timeout=min(waits) if waits else None)

    def done(self, key: Hashable) -> None:
        with self._cond:
            self._processing.discard(key)
            if key in self._dirty:
                self._dirty.discard(key)
                if key not in self._queued:
                    self._queued.add(key)
                    self._queue.append(key)
                    self._enqueued_at.setdefault(key, time.monotonic())
                    self._cond.notify()

    def shutdown(self) -> None:
        with self._cond:
            self._shutdown = True
            self._trace_ctx.clear()
            self._claimed_ctx.clear()
            self._enqueued_at.clear()
            self._cond.notify_all()

    def __len__(self) -> int:
        with self._cond:
            return len(self._queue)
