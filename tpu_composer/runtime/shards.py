"""Shard leases: scale the control plane past one active replica.

Every layer below this one — informer cache, dispatcher, crash-consistent
adoption, self-healing repair — assumed a single active leader, so the
operator was both a single point of failure and a single-process throughput
ceiling. This module generalizes ``runtime/leases.py`` single-leader
election into K *shard leases* (``shard-0..K-1``): N operator replicas each
CAS-acquire a balanced subset, a stable consistent-hash mapping
(:func:`shard_for`, crc32 — PYTHONHASHSEED-independent like the kubestore
RV digest) routes every object key to exactly one shard, and ownership is
enforced end-to-end (controller queues, syncer passes, dispatcher lanes,
the fabric write path). The design follows the composable-controller
argument of the Kubernetes Network Driver Model (arXiv:2506.23628):
partition device ownership rather than funnel it through one reconciler —
and the 32-GPU composable-system scaling study (arXiv:2404.06467), where
control-plane serialization dominates at scale.

Three properties carry the robustness story:

- **Handoff, not restart.** Acquiring a shard fires ``on_acquire``
  callbacks before the serving resync floods the queues; cmd/main wires
  the PR 5 cold-start adoption pass there, scoped to the shard's keys —
  so failover and rebalancing reuse exactly the machinery the
  kill–restart soak proves.
- **Fencing on loss.** A replica whose renewals fail past the
  renew-deadline (measured on the MONOTONIC clock — wall jumps must not
  keep a partitioned owner alive) drops ownership and fires ``on_lose``
  (cmd/main purges that shard's dispatcher lanes) strictly before the
  lease becomes stealable by a successor — the shard-level twin of the
  single-leader deposed fencing.
- **Observation-based expiry.** A contender steals a shard only after
  *its own monotonic clock* has watched the incumbent's ``renew_time``
  stay unchanged for a full lease duration (client-go's observedRenewTime
  discipline) — a skewed or jumped wall clock on either side can neither
  hasten nor indefinitely delay a steal.

Membership: each replica also renews one ``member`` lease, so replicas
holding zero shards (hot standbys) stay visible to the balance target
``ceil(K / live_members)``. The rebalancer sheds one shard per tick when
this replica holds more than the target AND the fleet spread is >1 off
balance — a returning replica is handed work without thrash.

``--shards 1`` (the default in cmd/main) never constructs any of this:
the single-leader path is untouched, bit-identical to every prior PR.
"""

from __future__ import annotations

import logging
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from tpu_composer.api.lease import Lease, LeaseSpec
from tpu_composer.api.meta import ObjectMeta, now_iso
from tpu_composer.runtime import tracing
from tpu_composer.runtime.leases import (
    RenewObservation,
    default_identity,
    sanitize_identity as _sanitize,
)
from tpu_composer.runtime.metrics import (
    shard_handoffs_total,
    shard_ownership_gauge,
)
from tpu_composer.runtime.store import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    StoreError,
)

SHARD_ELECTION_ID = "c5744f42.tpu.composer.dev"


def shard_for(name: str, num_shards: int) -> int:
    """Stable object-key → shard mapping. crc32, not hash(): the mapping
    must be identical across replicas, restarts and PYTHONHASHSEED (the
    same reason kubestore digests opaque resourceVersions with crc32) —
    two replicas disagreeing on a key's shard is a double-attach."""
    if num_shards <= 1:
        return 0
    return zlib.crc32(name.encode("utf-8")) % num_shards


class ShardFencedError(Exception):
    """Raised by a fabric write path whose key's shard this replica no
    longer owns — the mutation must not be issued. Quiet-exception in the
    controllers: the key requeues under backoff and the worker-side
    ownership filter drops it; the new owner drives the op via its scoped
    adoption pass reading the same durable intent."""


class ShardOwnership:
    """Thread-safe view of the shards this replica currently serves.

    ``None`` everywhere a component accepts an ownership handle means
    "unsharded" — no filtering, today's single-leader behavior.
    """

    def __init__(self, num_shards: int) -> None:
        self.num_shards = max(1, int(num_shards))
        self._lock = threading.Lock()
        self._owned: Set[int] = set()

    def owned(self) -> Set[int]:
        with self._lock:
            return set(self._owned)

    def owns_shard(self, shard: int) -> bool:
        with self._lock:
            return shard in self._owned

    def owns_key(self, name: str) -> bool:
        return self.owns_shard(shard_for(name, self.num_shards))

    # elector-internal mutators -----------------------------------------
    def _add(self, shard: int) -> None:
        with self._lock:
            self._owned.add(shard)

    def _discard(self, shard: int) -> None:
        with self._lock:
            self._owned.discard(shard)


def _ceil_div(a: int, b: int) -> int:
    return -(-a // max(1, b))


class ShardLeaseElector:
    """K shard leases + one member lease per replica, over any Store.

    Interface-compatible with the Manager's elector slot
    (``acquire(stop_event)/try_acquire()/release()/is_leader/lock_path``)
    — but ``is_leader`` stays True for the process lifetime: losing a
    shard fences and hands off THAT shard; it never deposes the replica,
    which keeps running as a hot standby re-acquiring work as leases free
    up. Tests may drive :meth:`tick` directly for determinism instead of
    starting the renew thread.
    """

    def __init__(
        self,
        # Duck-typed Store/KubeStore/CachedClient (same contract as
        # LeaseElector: get/create/update + the CAS error taxonomy).
        store: Any,
        num_shards: int,
        identity: str = "",
        name: str = SHARD_ELECTION_ID,
        lease_duration_s: float = 15.0,
        renew_period_s: float = 5.0,
        renew_deadline_s: float = 0.0,
        expected_replicas: int = 0,
    ) -> None:
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        self.store = store
        self.num_shards = num_shards
        self.name = name
        self.identity = identity or default_identity()
        self.lease_duration_s = lease_duration_s
        self.renew_period_s = renew_period_s
        if renew_deadline_s <= 0:
            renew_deadline_s = lease_duration_s * 2.0 / 3.0
        if renew_deadline_s >= lease_duration_s:
            raise ValueError(
                f"renew_deadline_s ({renew_deadline_s}) must be < "
                f"lease_duration_s ({lease_duration_s})"
            )
        self.renew_deadline_s = renew_deadline_s
        # Startup damping: during the first lease_duration after start,
        # cap acquisition at ceil(K/expected_replicas) so replica-1 of a
        # rolling N-replica deploy doesn't seize every shard only to shed
        # (and hand off) most of them moments later. 0/1 disables.
        self.expected_replicas = max(0, expected_replicas)
        self.ownership = ShardOwnership(num_shards)
        #: fired ONCE per tick with every shard won that tick
        #: ({shard: reason}), after the CAS lands and ownership flips on
        #: (so the dispatcher's owns-gate accepts re-driven work), BEFORE
        #: the serving resync — the scoped-adoption slot. Batched so a
        #: K-shard bootstrap costs one store list + one fabric listing,
        #: not K. A callback failure is logged, not fatal (reconcile-path
        #: safety nets converge).
        self.on_acquire: List[Callable[[Dict[int, str]], None]] = []
        #: fired once per tick with the set of shards just won, after
        #: on_acquire — the resync slot (re-enqueue the shards' keys into
        #: running controllers).
        self.on_ready: List[Callable[[Set[int]], None]] = []
        #: fired with (shard, reason) AFTER ownership flips off — the
        #: fencing slot (purge dispatcher lanes for the shard's keys).
        self.on_lose: List[Callable[[int, str], None]] = []
        self.log = logging.getLogger("ShardLeaseElector")
        self.lock_path = f"lease/{name} x{num_shards}"
        self._member_name = f"member.{_sanitize(self.identity)}.{name}"
        self._tick_lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._first_tick = threading.Event()
        self._started_mono: Optional[float] = None
        # shard -> monotonic time of the last successful renewal (the
        # fencing clock — wall-time jumps cannot move it).
        self._last_renew: Dict[int, float] = {}
        # lease name -> what we saw + when we first saw THAT (holder,
        # renew_time) pair on our monotonic clock.
        self._obs: Dict[str, RenewObservation] = {}
        self._failing = False  # fast-retry cadence while renewals fail
        #: Tag the renew thread's trace events (adopt spans from the
        #: on_acquire hooks) with this replica's identity pid. Default on
        #: for direct harness use; cmd/main flips it off under --no-fleet
        #: so the escape hatch leaves every event on plain os.getpid().
        self.tag_traces = True

    # ------------------------------------------------------------------
    def shard_lease_name(self, shard: int) -> str:
        return f"shard-{shard}.{self.name}"

    def owned_shards(self) -> Set[int]:
        return self.ownership.owned()

    @property
    def is_leader(self) -> bool:
        # Shard mode never deposes the whole replica: a shard loss fences
        # that shard; the process stays up as a standby. The Manager
        # watchdog therefore never fires for a shard elector.
        return not self._stop.is_set()

    # ------------------------------------------------------------------
    # lease bookkeeping
    # ------------------------------------------------------------------
    def _observe(self, lease_name: str, lease: Optional[Lease], now: float) -> RenewObservation:
        holder = lease.spec.holder_identity if lease is not None else ""
        renew = lease.spec.renew_time if lease is not None else ""
        obs = RenewObservation.advance(
            self._obs.get(lease_name), holder, renew, now
        )
        self._obs[lease_name] = obs
        return obs

    def _observed_expired(self, lease: Lease, obs: RenewObservation, now: float) -> bool:
        """Expired by OUR observation clock (RenewObservation, shared with
        the single-leader elector's steal gate): the (holder, renew_time)
        pair has sat unchanged for longer than the lease's advertised
        duration. Wall-clock stamps are never compared against wall-clock
        now — a jumped clock on either side cannot force an early steal."""
        return obs.expired(lease.spec.lease_duration_seconds, now)

    def _live_members(
        self, leases: Dict[str, Lease], now: float
    ) -> Tuple[Set[str], Dict[str, int]]:
        """(live replica identities, live shard-lease counts per holder).

        A replica is live if it renews a member lease OR holds any
        unexpired shard lease (covers electors that predate membership).
        Zero-holders matter: the balance target must see a hot standby.
        """
        live: Set[str] = {self.identity}
        counts: Dict[str, int] = {}
        for lease_name, lease in leases.items():
            obs = self._obs.get(lease_name)
            if obs is None:
                obs = self._observe(lease_name, lease, now)
            if not lease.spec.holder_identity:
                continue
            if self._observed_expired(lease, obs, now):
                continue
            if lease_name.startswith("member."):
                live.add(lease.spec.holder_identity)
            elif lease_name.startswith("shard-"):
                live.add(lease.spec.holder_identity)
                counts[lease.spec.holder_identity] = (
                    counts.get(lease.spec.holder_identity, 0) + 1
                )
        return live, counts

    # ------------------------------------------------------------------
    # the tick
    # ------------------------------------------------------------------
    def tick(self) -> None:
        """One full pass: membership heartbeat, renew owned shards (fence
        on deadline), shed for balance, acquire free/expired shards up to
        the balance target. Safe to call directly (tests) or from the
        renew thread."""
        with self._tick_lock:
            self._tick_locked()
        self._first_tick.set()

    def _tick_locked(self) -> None:
        now = time.monotonic()
        if self._started_mono is None:
            self._started_mono = now
        try:
            leases = {
                l.metadata.name: l
                for l in self.store.list(Lease)
                if l.metadata.name.endswith(self.name)
            }
        except StoreError as e:
            # Store dark: every owned shard's renewal is failing. Check
            # the monotonic fencing deadline per shard and stand down the
            # ones we can no longer prove are ours.
            self.log.warning("shard lease listing failed: %s", e)
            self._failing = True
            for shard in sorted(self.ownership.owned()):
                if now - self._last_renew.get(shard, now) >= self.renew_deadline_s:
                    self._lose(shard, "fenced")
            return
        for lease_name, lease in leases.items():
            self._observe(lease_name, lease, now)
        # Observations of deleted leases would otherwise accrete forever
        # across member churn (each crashed incarnation leaves a name).
        for stale in [n for n in self._obs if n not in leases]:
            del self._obs[stale]
        self._failing = False
        self._renew_member(leases, now)
        self._gc_dead_members(leases, now)
        live, counts = self._live_members(leases, now)
        target = _ceil_div(self.num_shards, len(live))
        if (
            self.expected_replicas > 1
            and now - self._started_mono < self.lease_duration_s
        ):
            target = min(
                target, _ceil_div(self.num_shards, self.expected_replicas)
            )
        self._renew_owned(leases, now)
        self._maybe_shed(leases, live, counts, now)
        self._maybe_acquire(leases, live, target, now)
        # A multi-shard win runs one scoped adoption pass per shard inside
        # the acquire hooks (store + fabric listings) — at real apiserver
        # RTTs that can eat a sizable slice of the renew period, and the
        # NEXT tick's renewals would land late enough to creep toward the
        # fencing deadline. Re-renew in the same tick when acquisition ran
        # long, so handoff work can never starve the shards already held
        # into self-fencing. (`leases` carries the post-renew objects, so
        # the CAS preconditions are current.)
        if time.monotonic() - now > self.renew_period_s / 2:
            self._renew_owned(leases, time.monotonic())
        self._export()

    def _renew_member(self, leases: Dict[str, Lease], now: float) -> None:
        stamp = now_iso()
        lease = leases.get(self._member_name)
        try:
            if lease is None:
                self.store.create(Lease(
                    metadata=ObjectMeta(name=self._member_name),
                    spec=LeaseSpec(
                        holder_identity=self.identity,
                        lease_duration_seconds=max(1, round(self.lease_duration_s)),
                        acquire_time=stamp,
                        renew_time=stamp,
                    ),
                ))
            else:
                lease.spec.holder_identity = self.identity
                lease.spec.renew_time = stamp
                self.store.update(lease)
        except (AlreadyExistsError, ConflictError):
            pass  # racing our own previous incarnation — next tick wins
        except StoreError as e:
            self._failing = True
            self.log.warning("member heartbeat failed: %s", e)

    def _gc_dead_members(self, leases: Dict[str, Lease], now: float) -> None:
        """Retire heartbeat Leases of dead incarnations. The identity
        embeds a per-boot uuid, so a kill -9'd replica never deletes its
        own member lease — without this sweep every crash leaks one Lease
        into the store (and one observation into every live replica)
        forever, and the listing that gates each renewal tick grows
        monotonically with pod churn. Conservative threshold (2x lease
        duration past our first observation of the final renew stamp):
        deleting a merely-partitioned replica's heartbeat is also safe —
        it re-creates the lease on its first healed tick."""
        for lease_name in list(leases):
            if not lease_name.startswith("member."):
                continue
            if lease_name == self._member_name:
                continue
            lease = leases[lease_name]
            obs = self._obs.get(lease_name)
            if obs is None:
                continue
            dead_for = now - obs.first_mono
            if dead_for <= 2 * max(
                1.0, float(lease.spec.lease_duration_seconds)
            ):
                continue
            try:
                self.store.delete(Lease, lease_name)
                del leases[lease_name]
                self._obs.pop(lease_name, None)
                self.log.info("retired dead member heartbeat %s", lease_name)
            except (NotFoundError, ConflictError):
                del leases[lease_name]
                self._obs.pop(lease_name, None)
            except StoreError:
                pass  # next tick retries

    def _renew_owned(self, leases: Dict[str, Lease], now: float) -> None:
        for shard in sorted(self.ownership.owned()):
            lease = leases.get(self.shard_lease_name(shard))
            if lease is None or lease.spec.holder_identity != self.identity:
                # Stolen (we must have been expired) or deleted out from
                # under us — the successor may already be serving. Stand
                # down NOW; the fencing margin absorbed the gap.
                self._lose(shard, "deposed")
                continue
            lease.spec.renew_time = now_iso()
            try:
                updated = self.store.update(lease)
                if updated is not None:
                    leases[lease.metadata.name] = updated
                    self._observe(lease.metadata.name, updated, now)
                self._last_renew[shard] = now
            except (ConflictError, NotFoundError, StoreError) as e:
                self._failing = True
                failing_for = now - self._last_renew.get(shard, now)
                self.log.warning(
                    "shard %d renew failed (%.1fs): %s", shard, failing_for, e
                )
                # Monotonic fencing deadline, the same contract as the
                # single-leader elector: stop serving the shard strictly
                # before its lease becomes stealable.
                if failing_for >= self.renew_deadline_s:
                    self._lose(shard, "fenced")

    def _maybe_shed(
        self,
        leases: Dict[str, Lease],
        live: Set[str],
        counts: Dict[str, int],
        now: float,
    ) -> None:
        owned = self.ownership.owned()
        target = _ceil_div(self.num_shards, len(live))
        if len(owned) <= target:
            return
        min_held = min((counts.get(m, 0) for m in live), default=0)
        if len(owned) - min_held <= 1:
            return  # spread within 1 — balanced enough, don't thrash
        # Shed ONE shard per tick (gentle: each handoff costs the new
        # owner a scoped adoption pass); highest shard id for determinism.
        shard = max(owned)
        self._lose(shard, "rebalance")
        self._release_shard_lease(shard)

    def _maybe_acquire(
        self,
        leases: Dict[str, Lease],
        live: Set[str],
        target: int,
        now: float,
    ) -> None:
        # Rotate the scan start by identity so N booting replicas don't
        # all CAS shard-0 first.
        start = zlib.crc32(self.identity.encode()) % self.num_shards
        owned_before = len(self.ownership.owned())
        wins: Dict[int, str] = {}
        for off in range(self.num_shards):
            shard = (start + off) % self.num_shards
            if self.ownership.owns_shard(shard):
                continue
            lease_name = self.shard_lease_name(shard)
            lease = leases.get(lease_name)
            holder = lease.spec.holder_identity if lease is not None else ""
            dead_holder = bool(holder) and holder not in live
            # Balance gates only FREE shards (bootstrap/handoff). A shard
            # whose holder is dead is taken unconditionally — availability
            # beats balance, and the rebalancer evens things out later.
            if not dead_holder and owned_before + len(wins) >= target:
                continue
            stamp = now_iso()
            try:
                if lease is None:
                    created = self.store.create(Lease(
                        metadata=ObjectMeta(name=lease_name),
                        spec=LeaseSpec(
                            holder_identity=self.identity,
                            lease_duration_seconds=max(1, round(self.lease_duration_s)),
                            acquire_time=stamp,
                            renew_time=stamp,
                        ),
                    ))
                    if created is not None:
                        leases[lease_name] = created
                    wins[shard] = "bootstrap"
                    continue
                obs = self._obs.get(lease_name) or self._observe(lease_name, lease, now)
                if not self._observed_expired(lease, obs, now):
                    continue
                lease.spec.holder_identity = self.identity
                lease.spec.acquire_time = stamp
                lease.spec.renew_time = stamp
                lease.spec.lease_transitions += 1
                updated = self.store.update(lease)  # CAS via resourceVersion
                leases[lease_name] = updated if updated is not None else lease
                wins[shard] = "failover" if holder else "handoff"
            except (AlreadyExistsError, ConflictError):
                continue  # another replica won this shard's race
            except StoreError as e:
                self._failing = True
                self.log.warning("shard %d acquire failed: %s", shard, e)
        if wins:
            self._serve_won(wins, now)

    # ------------------------------------------------------------------
    def _serve_won(self, wins: Dict[int, str], now: float) -> None:
        """Flip every shard won this tick on, then fire ONE batched
        on_acquire + on_ready round.

        Ownership flips ON before the on_acquire hooks: the scoped
        adoption pass inside them re-drives in-flight ops through THIS
        replica's dispatcher, whose owns-gate would silently discard the
        submissions if the shards still read as unowned. The serving
        resync (on_ready, which floods the controller queues with the
        shards' keys) still runs strictly after adoption; the only
        reconciles that can slip in between are watch-event-triggered
        ones, and those are safe by construction — idempotent verbs plus
        the durable intent nonce, the same contract that protects the
        no-adoption (hook-failure) path. Batching matters at bootstrap: a
        lone replica winning all K shards runs one adoption pass (one
        store list + one fabric listing) and one resync, not K of each —
        which is also what keeps a multi-shard win from starving renewals
        of the shards already held."""
        for shard, reason in wins.items():
            self._last_renew[shard] = now
            self.log.info("acquired shard %d (%s)", shard, reason)
            shard_handoffs_total.inc(reason=reason)
            self.ownership._add(shard)
            shard_ownership_gauge.set(1, shard=str(shard))
        for cb in self.on_acquire:
            try:
                cb(dict(wins))
            except Exception:
                self.log.exception(
                    "on_acquire hook failed for shards %s; relying on"
                    " reconcile-path recovery", sorted(wins)
                )
        for cb in self.on_ready:
            try:
                cb(set(wins))
            except Exception:
                self.log.exception(
                    "on_ready hook failed for shards %s", sorted(wins)
                )

    def _lose(self, shard: int, reason: str) -> None:
        # Ownership OFF first: controllers and the fabric write path stop
        # accepting the shard's keys before the fencing callbacks run.
        self.ownership._discard(shard)
        self._last_renew.pop(shard, None)
        self.log.warning("lost shard %d (%s)", shard, reason)
        shard_handoffs_total.inc(reason=reason)
        shard_ownership_gauge.set(0, shard=str(shard))
        for cb in self.on_lose:
            try:
                cb(shard, reason)
            except Exception:
                self.log.exception("on_lose hook failed for shard %d", shard)

    def _release_shard_lease(self, shard: int) -> None:
        """CAS-clear one shard lease, guarded on identity + rv: a deposed
        replica can never delete a successor's lease."""
        try:
            lease = self.store.try_get(Lease, self.shard_lease_name(shard))
            if lease is not None and lease.spec.holder_identity == self.identity:
                lease.spec.holder_identity = ""
                lease.spec.renew_time = ""
                self.store.update(lease)
        except ConflictError:
            pass  # a successor CAS'd in between read and write — theirs now
        except StoreError:
            pass  # expiry frees it

    def _export(self) -> None:
        owned = self.ownership.owned()
        for shard in range(self.num_shards):
            shard_ownership_gauge.set(
                1 if shard in owned else 0, shard=str(shard)
            )

    # ------------------------------------------------------------------
    # elector interface (Manager slot)
    # ------------------------------------------------------------------
    def try_acquire(self) -> bool:
        self.tick()
        return True

    def acquire(
        self,
        poll_interval: float = 0.5,
        stop_event: Optional[threading.Event] = None,
    ) -> bool:
        """Start the renew loop and block until the first full tick has
        completed (unlike the single-leader elector this returns even with
        zero shards held — a standby replica still serves /healthz and
        acquires work the moment leases free up)."""
        self.start()
        while not self._first_tick.wait(timeout=poll_interval):
            if stop_event is not None and stop_event.is_set():
                return False
            if self._stop.is_set():
                return False
        return True

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="shard-lease-renew", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        # The renew thread runs the scoped-adoption on_acquire hooks, whose
        # adopt spans must carry THIS replica's trace pid — a failover's
        # post-crash adoption renders as the stealing replica's process in
        # a merged fleet trace, not as an anonymous shared pid.
        if self.tag_traces:
            tracing.bind_thread(self.identity)
        fail_retry = min(1.0, self.renew_period_s)
        wait = 0.0  # first tick immediately
        while not self._stop.wait(wait):
            try:
                self.tick()
            except Exception:
                self.log.exception("shard tick failed")
            wait = fail_retry if self._failing else self.renew_period_s

    def release(self) -> None:
        """Voluntary stand-down: fence every owned shard, CAS-clear its
        lease (instant failover for successors) and retire the member
        heartbeat. Safe to call repeatedly."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=self.renew_period_s + 1)
            self._thread = None
        with self._tick_lock:
            for shard in sorted(self.ownership.owned()):
                self._lose(shard, "released")
                self._release_shard_lease(shard)
            try:
                self.store.delete(Lease, self._member_name)
            except (NotFoundError, StoreError):
                pass  # expiry retires the heartbeat
