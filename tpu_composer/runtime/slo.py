"""SLO engine: declarative objectives over existing histograms, with
rolling multi-window burn-rate alerts.

The metrics layer already measures everything that matters — attach-to-
ready latency, completion-notification latency, queue wait, repair
time-to-replace — but until now they were passive gauges: nothing said
"this is now violating what we promised". This module turns them into
ENFORCED objectives, SRE-style:

- An :class:`Objective` is (histogram, threshold, target): "at least
  ``target`` of observations must land at or under ``threshold`` seconds"
  — e.g. attach-to-ready p99 <= 5s is ``target=0.99, threshold_s=5.0``.
  The error budget is ``1 - target``.
- The engine snapshots each histogram's cumulative (total, bad) counts on
  every evaluation tick (bad = observations over the threshold, taken
  from the bucket counts with in-bucket interpolation — no per-sample
  timestamps needed, the Prometheus recipe) and diffs them over two
  rolling windows: a FAST window (reactivity + recovery) and a SLOW
  window (blip filtering).
- Burn rate per window = (bad/total)/budget: 1.0 means consuming exactly
  the error budget. The alert FIRES when both windows exceed
  ``burn_threshold`` (the classic multi-window AND — a blip can spike the
  fast window alone; a real regression saturates both) and CLEARS when
  the fast window drops back under it (the slow window decays too slowly
  to gate recovery). Edges emit a controller Event (SloBreached /
  SloRecovered), level-set ``tpuc_slo_breached{slo}``, and both windows
  continuously export ``tpuc_slo_burn_rate{slo,window}``.
- ``/debug/slo`` (manager health port) serves the whole state as JSON;
  the crash hooks dump the same snapshot to $TPUC_SLO_FILE so soak
  failure artifacts carry it.

No traffic in a window means burn 0 for that window — an idle control
plane is not violating a latency objective. Defaults and --slo-* /
TPUC_SLO_* overrides are wired in cmd/main.py; ``TPUC_PROFILE=0``
disables evaluation along with the rest of the observatory.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from dataclasses import dataclass, field
from types import SimpleNamespace
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from tpu_composer.runtime.metrics import (
    Histogram,
    slo_breached,
    slo_burn_rate,
)

log = logging.getLogger("slo")

#: The most recently started engine (crash-hook dump target), like the
#: profiler's active instance.
_active: Optional["SloEngine"] = None


@dataclass
class Objective:
    """One latency objective over an existing histogram (all label sets
    aggregated — an objective spans every type/verb/phase)."""

    name: str
    histogram: Histogram
    threshold_s: float
    target: float  # fraction of observations that must be <= threshold_s
    description: str = ""

    @property
    def budget(self) -> float:
        return max(1e-6, 1.0 - self.target)

    def counts(self) -> Tuple[float, float]:
        """(total, bad) cumulative observation counts right now."""
        total = float(self.histogram.total_count())
        good = self.histogram.total_count_le(self.threshold_s)
        return total, max(0.0, total - good)


class GoodputObjective(Objective):
    """A goodput objective over the :class:`~tpu_composer.runtime.goodput.
    GoodputTracker`'s cumulative second counters instead of a histogram:
    total wall seconds are the event stream, lost (non-serving) seconds
    are the bad events, and ``target`` is the serving fraction promised
    (0.95 -> a 5% lost-time budget). Both counters are monotonic including
    in-progress accrual, so the burn-window diffing works unchanged —
    burn 1.0 means the fleet is losing wall time exactly at budget."""

    def __init__(
        self, tracker: Any, target: float = 0.95, name: str = "goodput"
    ) -> None:
        super().__init__(
            name=name,
            histogram=None,  # type: ignore[arg-type]
            threshold_s=0.0,  # not a latency objective
            target=target,
            description=(
                "goodput: Ready-serving share of accounted request wall"
                " time (queued/provisioning/degraded/repairing/migrating"
                " time is the lost share)"
            ),
        )
        self.tracker = tracker

    def counts(self) -> Tuple[float, float]:
        return self.tracker.counts()


class _SloRef:
    """Event-recorder shim: breaches are cluster-scoped, not per-CR."""

    KIND = "SLO"

    def __init__(self, name: str) -> None:
        self.metadata = SimpleNamespace(name=name)


@dataclass
class _State:
    # ring of (t, total, bad) snapshots, oldest first; pruned to one entry
    # past the slow window so every window always has a baseline anchor.
    snaps: Deque[Tuple[float, float, float]] = field(
        default_factory=collections.deque
    )
    breached: bool = False
    since: Optional[float] = None  # monotonic t of the last edge
    fast_burn: float = 0.0
    slow_burn: float = 0.0


class SloEngine:
    def __init__(
        self,
        objectives: Optional[List[Objective]] = None,
        # Duck-typed events recorder (runtime/events.py): only .event()
        # is used, for the SloBreached/SloRecovered edges.
        recorder: Optional[Any] = None,
        fast_window: float = 60.0,
        slow_window: float = 600.0,
        burn_threshold: float = 2.0,
        eval_period: float = 5.0,
    ) -> None:
        self.objectives = (
            objectives if objectives is not None else default_objectives()
        )
        self.recorder = recorder
        self.fast_window = fast_window
        self.slow_window = slow_window
        self.burn_threshold = burn_threshold
        self.eval_period = eval_period
        self._lock = threading.Lock()
        self._state: Dict[str, _State] = {
            o.name: _State() for o in self.objectives
        }
        # Breach-Event annotators: objective name -> zero-arg callable
        # returning extra context for the alert message ("" = nothing).
        # cmd/main wires the queue-wait objective to the decision ledger's
        # dominant hold-back reason, so the alert names its probable cause
        # instead of just its symptom.
        self.annotators: Dict[str, Callable[[], str]] = {}

    # ------------------------------------------------------------------
    def run(self, stop_event: threading.Event) -> None:
        """Manager runnable: evaluate on a fixed cadence. The first pass
        runs immediately — it is the t=0 baseline snapshot; without it,
        observations landing inside the first eval period would be
        swallowed into the first snapshot's cumulative counts and never
        show up as a delta (a breach in the process's first seconds would
        be invisible)."""
        global _active
        _active = self
        while True:
            try:
                self.evaluate()
            except Exception:  # pragma: no cover - must never die
                log.exception("slo evaluation failed")
            if stop_event.wait(self.eval_period):
                return

    @staticmethod
    def _burn(
        snaps: Deque[Tuple[float, float, float]],
        now: float,
        window: float,
        budget: float,
    ) -> Tuple[float, float, float]:
        """Burn rate over [now-window, now]: diff the newest snapshot
        against the latest one at or before the window start (falling back
        to the oldest — a young process's window is its whole life)."""
        if not snaps:
            return 0.0, 0.0, 0.0
        t_now, total_now, bad_now = snaps[-1]
        base = snaps[0]
        for s in snaps:
            if s[0] <= now - window:
                base = s
            else:
                break
        d_total = total_now - base[1]
        d_bad = bad_now - base[2]
        if d_total <= 0:
            return 0.0, 0.0, 0.0
        # Clamp, don't trust, a shrinking bad count: a FLEET series can go
        # backwards when a dead replica's snapshot ages out of the merge
        # mid-window — a negative burn rate would read as "earning budget
        # back", which no objective ever does.
        d_bad = max(0.0, d_bad)
        return (d_bad / d_total) / budget, d_total, d_bad

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One evaluation pass; ``now`` is injectable for deterministic
        tests (monotonic seconds). Returns the /debug/slo snapshot."""
        now = time.monotonic() if now is None else now
        out: Dict[str, Any] = {
            "fast_window_s": self.fast_window,
            "slow_window_s": self.slow_window,
            "burn_threshold": self.burn_threshold,
            "objectives": {},
        }
        for obj in self.objectives:
            total, bad = obj.counts()
            with self._lock:
                st = self._state[obj.name]
                st.snaps.append((now, total, bad))
                horizon = now - self.slow_window
                while len(st.snaps) > 2 and st.snaps[1][0] <= horizon:
                    st.snaps.popleft()
                fast, f_total, f_bad = self._burn(
                    st.snaps, now, self.fast_window, obj.budget
                )
                slow, s_total, s_bad = self._burn(
                    st.snaps, now, self.slow_window, obj.budget
                )
                st.fast_burn, st.slow_burn = fast, slow
                was = st.breached
                if not was and (
                    fast >= self.burn_threshold and slow >= self.burn_threshold
                ):
                    st.breached = True
                    st.since = now
                elif was and fast < self.burn_threshold:
                    st.breached = False
                    st.since = now
                breached = st.breached
                edge = breached != was
                since = st.since
            slo_burn_rate.set(round(fast, 4), slo=obj.name, window="fast")
            slo_burn_rate.set(round(slow, 4), slo=obj.name, window="slow")
            slo_breached.set(1.0 if breached else 0.0, slo=obj.name)
            if edge:
                self._emit_edge(obj, breached, fast, slow)
            out["objectives"][obj.name] = {
                "description": obj.description,
                "threshold_s": obj.threshold_s,
                "target": obj.target,
                "budget": round(obj.budget, 6),
                "breached": breached,
                "since_s_ago": round(now - since, 3) if since is not None else None,
                "windows": {
                    "fast": {"burn_rate": round(fast, 4),
                             "events": f_total, "bad": f_bad},
                    "slow": {"burn_rate": round(slow, 4),
                             "events": s_total, "bad": s_bad},
                },
            }
        return out

    def _emit_edge(
        self, obj: Objective, breached: bool, fast: float, slow: float
    ) -> None:
        if breached:
            # Latency objectives render the percentile promise; ratio
            # objectives (threshold_s <= 0, e.g. goodput) render the
            # fraction promise — "(p95 <= 0s)" would read as nonsense.
            promise = (
                f"(p{obj.target * 100:g} <= {obj.threshold_s:g}s)"
                if obj.threshold_s > 0
                else f"(>= {obj.target * 100:g}% good)"
            )
            msg = (
                f"{obj.name}: error budget burning at {fast:.1f}x (fast)"
                f" / {slow:.1f}x (slow) — {obj.description or 'objective'}"
                f" {promise} violated"
            )
            annotate = self.annotators.get(obj.name)
            if annotate is not None:
                try:
                    extra = annotate()
                except Exception:  # pragma: no cover - defensive
                    extra = ""
                if extra:
                    msg += f"; probable cause: {extra}"
            log.warning("SLO BREACH %s", msg)
        else:
            msg = (
                f"{obj.name}: fast-window burn back under"
                f" {self.burn_threshold:g}x — alert cleared"
            )
            log.info("SLO recovered: %s", msg)
        if self.recorder is not None:
            try:
                self.recorder.event(
                    _SloRef(obj.name),
                    "Warning" if breached else "Normal",
                    "SloBreached" if breached else "SloRecovered",
                    msg,
                )
            except Exception:  # pragma: no cover
                log.exception("slo event emission failed")

    # ------------------------------------------------------------------
    def breached(self, name: str) -> bool:
        with self._lock:
            st = self._state.get(name)
            return bool(st and st.breached)

    def burn_rates(self, name: str) -> Tuple[float, float]:
        with self._lock:
            st = self._state.get(name)
            return (st.fast_burn, st.slow_burn) if st else (0.0, 0.0)

    def snapshot(self) -> Dict[str, Any]:
        """Current state WITHOUT advancing the rings (read-only: what
        /debug/slo serves between evaluation ticks)."""
        now = time.monotonic()
        out: Dict[str, Any] = {
            "fast_window_s": self.fast_window,
            "slow_window_s": self.slow_window,
            "burn_threshold": self.burn_threshold,
            "eval_period_s": self.eval_period,
            "objectives": {},
        }
        for obj in self.objectives:
            with self._lock:
                st = self._state[obj.name]
                out["objectives"][obj.name] = {
                    "description": obj.description,
                    "threshold_s": obj.threshold_s,
                    "target": obj.target,
                    "breached": st.breached,
                    "since_s_ago": (
                        round(now - st.since, 3) if st.since is not None else None
                    ),
                    "fast_burn": round(st.fast_burn, 4),
                    "slow_burn": round(st.slow_burn, 4),
                }
        return out


def default_objectives(
    attach_p99_s: float = 5.0,
    completion_p50_s: float = 1.0,
    queue_p99_s: float = 1.0,
    repair_p99_s: float = 120.0,
) -> List[Objective]:
    """The stock objectives over the histograms the repo already emits.
    A threshold <= 0 drops that objective (the per-objective off switch
    cmd/main exposes as --slo-*=0). Defaults sit on bucket boundaries of
    their histograms: ``total_count_le`` interpolates inside a bucket, so
    a mid-bucket threshold would count borderline observations
    fractionally — boundary-aligned thresholds keep bad counts integral."""
    from tpu_composer.runtime import metrics

    out: List[Objective] = []
    if attach_p99_s > 0:
        out.append(Objective(
            "attach_p99", metrics.attach_to_ready_seconds, attach_p99_s, 0.99,
            "attach-to-ready latency (CR creation to Running)",
        ))
    if completion_p50_s > 0:
        out.append(Objective(
            "completion_p50", metrics.fabric_completion_latency,
            completion_p50_s, 0.50,
            "fabric op completion notification (dispatcher submit to settle)",
        ))
    if queue_p99_s > 0:
        out.append(Objective(
            "queue_wait_p99", metrics.queue_wait_seconds, queue_p99_s, 0.99,
            "work-queue wait (enqueue to dequeue)",
        ))
    if repair_p99_s > 0:
        out.append(Objective(
            "repair_p99", metrics.repair_time_to_replace_seconds,
            repair_p99_s, 0.99,
            "self-healing time-to-replace (Degraded to replaced)",
        ))
    return out


def active() -> Optional["SloEngine"]:
    return _active


def dump_file(path: Optional[str] = None) -> Optional[str]:
    """Write the active engine's snapshot to ``path`` (default
    $TPUC_SLO_FILE) — the soak failure artifact twin of the profiler's
    ring dump. Never raises."""
    path = path or os.environ.get("TPUC_SLO_FILE")
    eng = _active
    if not path or eng is None:
        return None
    try:
        with open(path, "w") as f:
            json.dump(eng.snapshot(), f, indent=1)
    except (OSError, ValueError):
        return None
    return path
