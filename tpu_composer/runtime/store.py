"""In-process, watchable, persistent object store.

Plays the role the K8s API server + etcd play for the reference operator:

- optimistic concurrency via ``metadata.resource_version`` (update conflicts
  surface as ConflictError, the analog of a 409 that controller-runtime
  requeues on);
- a status subresource: ``update_status`` persists only ``status`` (the
  reference CRDs declare ``+kubebuilder:subresource:status``,
  composabilityrequest_types.go:82-84);
- finalizer-gated deletion: ``delete`` sets ``deletionTimestamp`` while
  finalizers remain, and the object is purged when the last finalizer is
  removed — exactly the lifecycle the reference's handleDeletingState relies
  on (composableresource_controller.go:418-434);
- label-selector listing (the reference lists children by
  ``app.kubernetes.io/managed-by``, composabilityrequest_controller.go:222-235);
- watches with ADDED/MODIFIED/DELETED events feeding controller work queues
  (analog of controller-runtime's source.Kind watches, cmd/main.go:167-194);
- optional file persistence, one JSON doc per object, making the object store
  itself the checkpoint/resume mechanism (SURVEY.md §5 "the CRDs *are* the
  checkpoint").

Objects handed out and accepted are deep-copied at the boundary, so callers
can mutate freely — same contract as client-go's cache + typed client.
"""

from __future__ import annotations

import copy
import json
import logging
import os
import queue
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, Iterable, List, Optional, Tuple, Type, TypeVar

from tpu_composer.api.meta import ApiObject, new_uid, now_iso
from tpu_composer.api.scheme import Scheme, default_scheme
from tpu_composer.runtime.contention import ObservedLock
from tpu_composer.runtime.metrics import (
    store_requests_total,
    store_watch_queue_depth,
)

T = TypeVar("T", bound=ApiObject)

#: Watcher queues are unbounded; past this depth the consumer is falling
#: behind and we say so (gauge + one warning per crossing) instead of
#: silently buffering events forever.
WATCH_QUEUE_WARN_DEPTH = 1024

_log = logging.getLogger("store")


class StoreError(Exception):
    pass


class NotFoundError(StoreError):
    pass


class AlreadyExistsError(StoreError):
    pass


class ConflictError(StoreError):
    """resourceVersion mismatch — caller must re-get and retry."""


ADDED = "ADDED"
MODIFIED = "MODIFIED"
DELETED = "DELETED"


def delete_tolerant(store: "Store", cls, name: str):
    """Delete ``name`` tolerating a concurrent purge, then re-read.

    Returns the surviving (terminating, finalizer-bearing) object, or None
    when it is already gone — either the delete hit 404 or the object had no
    finalizer and purged outright. Deletion-path reconcile steps use this so
    an object vanishing between the cache read and the API call means "done",
    not an exception — the reference wraps every deletion-path call in
    client.IgnoreNotFound (composableresource_controller.go:87,143,160;
    composabilityrequest_controller.go:153-157)."""
    try:
        store.delete(cls, name)
    except NotFoundError:
        return None
    return store.try_get(cls, name)


@dataclass
class WatchEvent:
    type: str  # ADDED | MODIFIED | DELETED
    obj: ApiObject


@dataclass
class _Watcher:
    """One subscription: its kind filter, queue, stable metric identity,
    and whether the depth warning already fired for the current backlog."""

    kind: Optional[str]
    q: "queue.Queue[WatchEvent]"
    label: str = ""
    warned: bool = field(default=False, compare=False)


# An admission hook runs inside create/update with (op, new_obj, old_obj) and
# may mutate new_obj or raise to reject. op ∈ {"CREATE", "UPDATE", "DELETE"}.
# Reference analog: the validating webhook registered at cmd/main.go:196-201.
AdmissionHook = Callable[[str, ApiObject, Optional[ApiObject]], None]


class Store:
    def __init__(
        self,
        scheme: Optional[Scheme] = None,
        persist_dir: Optional[str] = None,
        latency_s: float = 0.0,
    ) -> None:
        """``latency_s`` injects an apiserver-like round-trip delay at the
        entry of every CRUD call (outside the lock, so concurrent clients
        overlap their waits the way HTTP requests to a real apiserver do).
        Used by bench.py for the honest reference comparison: the reference
        pays a networked kube-apiserver on every store op, the in-proc store
        pays nanoseconds — the injected mode levels that."""
        self._scheme = scheme or default_scheme()
        self._latency_s = latency_s
        # Contention telemetry: the store lock serializes every CRUD call
        # and watch notification — wait/hold land in
        # tpuc_lock_wait_seconds{lock="store"} (reentrant: admission hooks
        # run inside create/update and may read back through the store).
        self._lock = ObservedLock("store", reentrant=True)
        # kind -> name -> object (all cluster-scoped, like the reference's
        # CRDs, +kubebuilder:resource:scope=Cluster). The per-kind secondary
        # index keeps ``list`` from scanning and sorting every kind's keys
        # on each call — list runs on every reconcile, caching on or off.
        self._by_kind: Dict[str, Dict[str, ApiObject]] = {}
        self._watchers: List[_Watcher] = []
        self._watch_seq = 0
        self._admission: List[Tuple[str, AdmissionHook]] = []  # (kind or "*", hook)
        self._rv_counter = 0
        self._persist_dir = persist_dir
        if persist_dir:
            self._load(persist_dir)

    @property
    def scheme(self) -> Scheme:
        return self._scheme

    # ------------------------------------------------------------------
    # persistence (checkpoint/resume)
    # ------------------------------------------------------------------
    def _obj_path(self, kind: str, name: str) -> str:
        assert self._persist_dir
        return os.path.join(self._persist_dir, kind, f"{name}.json")

    def _persist(self, obj: ApiObject) -> None:
        if not self._persist_dir:
            return
        path = self._obj_path(obj.KIND, obj.metadata.name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj.to_dict(), f, sort_keys=True)
        os.replace(tmp, path)

    def _unpersist(self, kind: str, name: str) -> None:
        if not self._persist_dir:
            return
        try:
            os.remove(self._obj_path(kind, name))
        except FileNotFoundError:
            pass

    def _load(self, persist_dir: str) -> None:
        if not os.path.isdir(persist_dir):
            return
        max_rv = 0
        for kind in os.listdir(persist_dir):
            kdir = os.path.join(persist_dir, kind)
            if not os.path.isdir(kdir):
                continue
            for fn in os.listdir(kdir):
                if not fn.endswith(".json"):
                    continue
                with open(os.path.join(kdir, fn)) as f:
                    obj = self._scheme.decode(json.load(f))
                self._by_kind.setdefault(obj.KIND, {})[obj.metadata.name] = obj
                max_rv = max(max_rv, obj.metadata.resource_version)
        self._rv_counter = max_rv

    # ------------------------------------------------------------------
    # admission + watch registration
    # ------------------------------------------------------------------
    def register_admission(self, kind: str, hook: AdmissionHook) -> None:
        """kind="*" applies to every kind."""
        with self._lock:
            self._admission.append((kind, hook))

    def watch(self, kind: Optional[str] = None) -> "queue.Queue[WatchEvent]":
        """Subscribe to events; kind=None receives everything.

        Returns an unbounded queue the caller drains. Existing objects are NOT
        replayed — controllers do their own initial list (same as
        controller-runtime's cache sync + initial reconcile wave, which our
        Controller base performs on start).
        """
        q: "queue.Queue[WatchEvent]" = queue.Queue()
        with self._lock:
            self._watch_seq += 1
            self._watchers.append(
                _Watcher(kind, q, label=f"{kind or '*'}-{self._watch_seq}")
            )
        return q

    def stop_watch(self, q: "queue.Queue[WatchEvent]") -> None:
        with self._lock:
            kept = []
            for w in self._watchers:
                if w.q is q:
                    store_watch_queue_depth.remove(watcher=w.label)
                else:
                    kept.append(w)
            self._watchers = kept

    def _notify(self, event_type: str, obj: ApiObject) -> None:
        snap = obj.deepcopy()
        for w in self._watchers:
            if w.kind is None or w.kind == obj.KIND:
                w.q.put(WatchEvent(event_type, snap))
                depth = w.q.qsize()
                store_watch_queue_depth.set(float(depth), watcher=w.label)
                if depth > WATCH_QUEUE_WARN_DEPTH:
                    if not w.warned:
                        w.warned = True
                        _log.warning(
                            "watcher %s queue depth %d exceeds %d —"
                            " consumer is falling behind",
                            w.label, depth, WATCH_QUEUE_WARN_DEPTH,
                        )
                elif depth <= WATCH_QUEUE_WARN_DEPTH // 2:
                    w.warned = False

    def _run_admission(self, op: str, new: ApiObject, old: Optional[ApiObject]) -> None:
        for kind, hook in list(self._admission):
            if kind == "*" or kind == new.KIND:
                hook(op, new, old)

    # ------------------------------------------------------------------
    # CRUD
    # ------------------------------------------------------------------
    def _next_rv(self) -> int:
        self._rv_counter += 1
        return self._rv_counter

    def _rtt(self) -> None:
        if self._latency_s:
            import time

            time.sleep(self._latency_s)

    def create(self, obj: T) -> T:
        store_requests_total.inc(verb="create", kind=obj.KIND)
        self._rtt()
        obj = obj.deepcopy()
        if not obj.metadata.name:
            raise StoreError("metadata.name is required")
        with self._lock:
            kind_objs = self._by_kind.setdefault(obj.KIND, {})
            if obj.metadata.name in kind_objs:
                raise AlreadyExistsError(f"{obj.KIND}/{obj.metadata.name} already exists")
            # Admission (mutating) runs before schema validation, matching the
            # K8s admission chain the reference's webhook participates in.
            self._run_admission("CREATE", obj, None)
            if hasattr(obj, "validate"):
                obj.validate()
            obj.metadata.uid = obj.metadata.uid or new_uid()
            obj.metadata.resource_version = self._next_rv()
            obj.metadata.generation = 1
            obj.metadata.creation_timestamp = obj.metadata.creation_timestamp or now_iso()
            obj.metadata.deletion_timestamp = None
            kind_objs[obj.metadata.name] = obj
            self._persist(obj)
            self._notify(ADDED, obj)
            return obj.deepcopy()

    def get(self, cls: Type[T], name: str) -> T:
        store_requests_total.inc(verb="get", kind=cls.KIND)
        self._rtt()
        with self._lock:
            try:
                obj = self._by_kind.get(cls.KIND, {})[name]
            except KeyError:
                raise NotFoundError(f"{cls.KIND}/{name} not found") from None
            return obj.deepcopy()  # type: ignore[return-value]

    def try_get(self, cls: Type[T], name: str) -> Optional[T]:
        try:
            return self.get(cls, name)
        except NotFoundError:
            return None

    def list(
        self,
        cls: Type[T],
        label_selector: Optional[Dict[str, str]] = None,
    ) -> List[T]:
        store_requests_total.inc(verb="list", kind=cls.KIND)
        self._rtt()
        with self._lock:
            # Per-kind index: only this kind's objects are touched — list
            # runs on every reconcile, so the old all-kinds scan+sort cost
            # O(total objects log total) per call even with caching off.
            out: List[T] = []
            for _, obj in sorted(self._by_kind.get(cls.KIND, {}).items()):
                if label_selector and any(
                    obj.metadata.labels.get(k) != v for k, v in label_selector.items()
                ):
                    continue
                out.append(obj.deepcopy())  # type: ignore[arg-type]
            return out

    def _check_conflict(self, stored: ApiObject, incoming: ApiObject) -> None:
        if incoming.metadata.resource_version != stored.metadata.resource_version:
            raise ConflictError(
                f"{incoming.KIND}/{incoming.metadata.name}: resourceVersion"
                f" {incoming.metadata.resource_version} != {stored.metadata.resource_version}"
            )

    def update(self, obj: T) -> T:
        """Update spec + metadata; status is preserved from the stored copy.

        If the object is terminating and this update removes the last
        finalizer, the object is purged (DELETED event) — K8s semantics.
        """
        store_requests_total.inc(verb="update", kind=obj.KIND)
        self._rtt()
        obj = obj.deepcopy()
        with self._lock:
            kind_objs = self._by_kind.get(obj.KIND, {})
            stored = kind_objs.get(obj.metadata.name)
            if stored is None:
                raise NotFoundError(f"{obj.KIND}/{obj.metadata.name} not found")
            self._check_conflict(stored, obj)
            self._run_admission("UPDATE", obj, stored.deepcopy())
            if hasattr(obj, "validate"):
                obj.validate()

            spec_changed = stored.spec.to_dict() != obj.spec.to_dict()  # type: ignore[attr-defined]
            obj.status = copy.deepcopy(stored.status)  # type: ignore[attr-defined]
            # Immutable/system-owned fields
            obj.metadata.uid = stored.metadata.uid
            obj.metadata.creation_timestamp = stored.metadata.creation_timestamp
            obj.metadata.deletion_timestamp = stored.metadata.deletion_timestamp
            obj.metadata.generation = stored.metadata.generation + (1 if spec_changed else 0)
            obj.metadata.resource_version = self._next_rv()

            if obj.metadata.deletion_timestamp is not None and not obj.metadata.finalizers:
                del kind_objs[obj.metadata.name]
                self._unpersist(obj.KIND, obj.metadata.name)
                self._notify(DELETED, obj)
                return obj.deepcopy()

            kind_objs[obj.metadata.name] = obj
            self._persist(obj)
            self._notify(MODIFIED, obj)
            return obj.deepcopy()

    def update_status(self, obj: T) -> T:
        """Persist only ``status`` (status subresource semantics)."""
        store_requests_total.inc(verb="update_status", kind=obj.KIND)
        self._rtt()
        obj = obj.deepcopy()
        with self._lock:
            kind_objs = self._by_kind.get(obj.KIND, {})
            stored = kind_objs.get(obj.metadata.name)
            if stored is None:
                raise NotFoundError(f"{obj.KIND}/{obj.metadata.name} not found")
            self._check_conflict(stored, obj)
            updated = stored.deepcopy()
            updated.status = obj.status  # type: ignore[attr-defined]
            updated.metadata.resource_version = self._next_rv()
            kind_objs[obj.metadata.name] = updated
            self._persist(updated)
            self._notify(MODIFIED, updated)
            return updated.deepcopy()  # type: ignore[return-value]

    def delete(self, cls: Type[T], name: str) -> None:
        """Finalizer-aware delete.

        With finalizers present: marks deletionTimestamp and emits MODIFIED so
        controllers run their teardown states (the reference's Cleaning /
        Detaching paths). Without: purges immediately.
        """
        store_requests_total.inc(verb="delete", kind=cls.KIND)
        self._rtt()
        with self._lock:
            kind_objs = self._by_kind.get(cls.KIND, {})
            stored = kind_objs.get(name)
            if stored is None:
                raise NotFoundError(f"{cls.KIND}/{name} not found")
            # Hooks get copies: a mutating hook must not corrupt canonical
            # state outside the rv/persist/notify path.
            self._run_admission("DELETE", stored.deepcopy(), stored.deepcopy())
            if stored.metadata.finalizers:
                if stored.metadata.deletion_timestamp is None:
                    updated = stored.deepcopy()
                    updated.metadata.deletion_timestamp = now_iso()
                    updated.metadata.resource_version = self._next_rv()
                    kind_objs[name] = updated
                    self._persist(updated)
                    self._notify(MODIFIED, updated)
                return
            del kind_objs[name]
            self._unpersist(cls.KIND, name)
            self._notify(DELETED, stored)

    # ------------------------------------------------------------------
    # helpers
    # ------------------------------------------------------------------
    def keys(self) -> Iterable[Tuple[str, str]]:
        with self._lock:
            return [
                (kind, name)
                for kind, objs in self._by_kind.items()
                for name in objs
            ]

    def __len__(self) -> int:
        with self._lock:
            return sum(len(objs) for objs in self._by_kind.values())
