"""Store circuit breaker + post-outage resync pacing.

The apiserver twin of ``fabric/breaker.py``: where the fabric breaker
protects the pool manager from a retry storm, this wraps the OBJECT STORE
(in-proc ``Store``, ``KubeStore``, or the ChaosStore around either) and
classifies its errors the same way:

- ``StoreError`` (transient 5xx / timeouts / the ChaosStore's blackout,
  and — via KubeStore's MuxError→StoreError mapping — every framed-wire
  transport death: a mux connection failing ALL its pending verbs at once
  lands the whole batch on the trip streak in one tick, so a partitioned
  or flapping wire trips the outage ride-through fast instead of bleeding
  one 30s timeout per verb) is a breaker failure; ``failure_threshold``
  consecutive ones trip OPEN;
- ``ConflictError`` / ``NotFoundError`` are the store WORKING — a 409 or
  404 is a healthy apiserver saying no, so they reset the failure streak
  and never trip the breaker.

While OPEN every wire verb fails fast with ``StoreError("store breaker
open ...")`` instead of paying a wire timeout — the controllers' existing
conflict/error requeue parks each key under decorrelated backoff, and
because this wrapper sits UNDER the CachedClient, reads keep serving from
the watch-fed informer at zero RTT for the whole outage. After
``reset_timeout`` (±20% jitter so N replicas don't probe in lockstep) one
HALF_OPEN probe is admitted; success closes, failure re-opens.

**Recovery pacing.** The close edge is where outages do their second
round of damage: every controller's backed-off keys wake within one
backoff quantum of heal and N controllers × K keys stampede the
just-recovered apiserver. On close, a global token bucket
(``resync_rate`` tokens/s, starting EMPTY) gates every wire verb for
``resync_window`` seconds — callers briefly sleep for a token
(``tpuc_resync_paced_total`` counts them), spreading the herd at a rate
the recovering store can absorb. Outside the window the bucket is
bypassed entirely: steady-state calls pay one lock acquire and nothing
else.

Metrics: ``tpuc_store_breaker_open`` (1 while open/half-open),
``tpuc_store_outage_seconds_total`` (settled at each close edge),
``tpuc_resync_paced_total``. ``/debug/storebreaker`` serves
:meth:`BreakingStore.snapshot`. Wired by cmd/main between
``build_store`` and ``maybe_cached`` (``--store-breaker`` /
``TPUC_STORE_BREAKER``, default on; =0 constructs none of this).
"""

from __future__ import annotations

import logging
import random
import threading
import time
from typing import Callable, List, Optional, Type, TypeVar

from tpu_composer.api.meta import ApiObject
from tpu_composer.runtime.metrics import (
    resync_paced_total,
    store_breaker_open,
    store_outage_seconds_total,
)
from tpu_composer.runtime.store import (
    ConflictError,
    NotFoundError,
    StoreError,
)

log = logging.getLogger("tpuc.storebreaker")

T = TypeVar("T", bound=ApiObject)

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class BreakingStore:
    """Store wrapper: circuit breaker + post-outage resync pacing.

    Duck-types the full Store surface (CRUD + watch + plumbing) like the
    ChaosStore it may wrap; only the CRUD verbs traverse the breaker —
    watches are the informer's lifeline and must keep (re)connecting
    through an outage, and plumbing (scheme, keys) never leaves the
    process.
    """

    def __init__(
        self,
        inner,
        failure_threshold: int = 5,
        reset_timeout: float = 5.0,
        resync_rate: float = 50.0,
        resync_window: float = 2.0,
        clock: Callable[[], float] = time.monotonic,
        sleep: Callable[[float], None] = time.sleep,
        rng: Optional[random.Random] = None,
    ) -> None:
        self._inner = inner
        self.failure_threshold = max(1, failure_threshold)
        self.reset_timeout = reset_timeout
        self.resync_rate = max(1.0, resync_rate)
        self.resync_window = max(0.0, resync_window)
        self._clock = clock
        self._sleep = sleep
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at: Optional[float] = None
        self._retry_at = 0.0
        self._probing = False  # one half-open probe in flight at a time
        #: token bucket, armed at each close edge: tokens accrue at
        #: resync_rate from EMPTY until pacing_until passes.
        self._tokens = 0.0
        self._tokens_at = 0.0
        self._pacing_until = 0.0
        self.trips = 0
        store_breaker_open.set(0)

    # ------------------------------------------------------------------
    # breaker state machine (caller holds no lock; methods take it)
    # ------------------------------------------------------------------
    def is_open(self) -> bool:
        with self._lock:
            return self._state != CLOSED

    def state(self) -> str:
        with self._lock:
            return self._state

    def probe(self) -> bool:
        """Active recovery probe for an IDLE control plane. The breaker
        normally heals on the next admitted call — but the overload
        governor's shed gate defers all work below the priority cutoff,
        and a plane whose only pending work is low-priority would starve
        the breaker of the very call that closes it: store healed,
        breaker open, everything shed, forever. The governor calls this
        each tick while the breaker is open; it is a fail-fast no-op
        until the jittered retry window passes (no wire attempt), then
        one cheap list of the scheme's first kind serves as the
        half-open probe. Returns True iff the breaker is closed after."""
        if not self.is_open():
            return True
        try:
            kinds = self.scheme.kinds()
        except Exception:
            return False
        if not kinds:
            return False
        try:
            self.list(self.scheme.lookup(kinds[0]))
        except StoreError:
            return False
        except Exception:
            # A non-store error still proves the wire answered.
            pass
        return not self.is_open()

    def _admit(self, verb: str) -> bool:
        """True if the call may hit the wire; False = fail fast."""
        with self._lock:
            if self._state == CLOSED:
                return True
            now = self._clock()
            if self._state == OPEN and now >= self._retry_at:
                self._state = HALF_OPEN
                self._probing = False
            if self._state == HALF_OPEN and not self._probing:
                self._probing = True  # this caller is the probe
                return True
            return False

    def _on_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state == CLOSED:
                return
            # HALF_OPEN probe succeeded (or a straggler landed): close,
            # settle the outage clock, arm the resync bucket.
            now = self._clock()
            if self._opened_at is not None:
                store_outage_seconds_total.inc(max(0.0, now - self._opened_at))
            self._state = CLOSED
            self._probing = False
            self._opened_at = None
            self._tokens = 0.0
            self._tokens_at = now
            self._pacing_until = now + self.resync_window
            store_breaker_open.set(0)
            log.info("store breaker closed; pacing resyncs for %.1fs",
                     self.resync_window)

    def _on_failure(self) -> None:
        with self._lock:
            now = self._clock()
            if self._state == HALF_OPEN:
                self._trip(now)  # probe failed — straight back to open
                return
            self._failures += 1
            if self._state == CLOSED and self._failures >= self.failure_threshold:
                self._trip(now)

    def _trip(self, now: float) -> None:
        # caller holds the lock
        if self._opened_at is None:
            self._opened_at = now
            self.trips += 1
        self._state = OPEN
        self._probing = False
        self._failures = 0
        # ±20% jitter so replicas sharing a dead apiserver spread probes.
        self._retry_at = now + self.reset_timeout * self._rng.uniform(0.8, 1.2)
        store_breaker_open.set(1)
        log.warning("store breaker OPEN (retry in ~%.1fs)", self.reset_timeout)

    # ------------------------------------------------------------------
    # resync pacing
    # ------------------------------------------------------------------
    def _pace(self) -> None:
        """Take a token from the post-heal bucket; sleeps (briefly) when
        the drain is running hot. No-op outside the resync window."""
        while True:
            with self._lock:
                now = self._clock()
                if now >= self._pacing_until:
                    return
                # Burst cap of 2: an idle stretch inside the window buys
                # at most two back-to-back calls, never a re-herd.
                self._tokens = min(
                    2.0,
                    self._tokens + (now - self._tokens_at) * self.resync_rate,
                )
                self._tokens_at = now
                # Epsilon: accrual is (elapsed * rate) float arithmetic, and
                # 0.1s * 10/s lands at 0.9999999999999964 — without the
                # tolerance the residual wait collapses toward zero and the
                # loop busy-spins on sub-nanosecond sleeps.
                if self._tokens >= 1.0 - 1e-9:
                    self._tokens = max(0.0, self._tokens - 1.0)
                    return
                wait = (1.0 - self._tokens) / self.resync_rate
            resync_paced_total.inc()
            self._sleep(min(max(wait, 1e-4), 0.25))

    # ------------------------------------------------------------------
    def _call(self, verb: str, fn: Callable, *args, **kwargs):
        self._pace()
        if not self._admit(verb):
            raise StoreError(
                f"store breaker open: {verb} rejected without a wire attempt"
            )
        try:
            result = fn(*args, **kwargs)
        except (ConflictError, NotFoundError):
            # The apiserver ANSWERED — 409/404 is a healthy store saying
            # no, so the streak resets (and a half-open probe closes).
            self._on_success()
            raise
        except StoreError:
            self._on_failure()
            raise
        self._on_success()
        return result

    # ------------------------------------------------------------------
    # Store interface (CRUD traverses the breaker; plumbing delegates)
    # ------------------------------------------------------------------
    @property
    def scheme(self):
        return self._inner.scheme

    def register_admission(self, kind, hook) -> None:
        self._inner.register_admission(kind, hook)

    def create(self, obj: T) -> T:
        return self._call("create", self._inner.create, obj)

    def get(self, cls: Type[T], name: str) -> T:
        return self._call("get", self._inner.get, cls, name)

    def try_get(self, cls: Type[T], name: str) -> Optional[T]:
        try:
            return self.get(cls, name)
        except NotFoundError:
            return None

    def list(self, cls: Type[T], label_selector=None) -> List[T]:
        return self._call("list", self._inner.list, cls, label_selector)

    def update(self, obj: T) -> T:
        return self._call("update", self._inner.update, obj)

    def update_status(self, obj: T) -> T:
        return self._call("update_status", self._inner.update_status, obj)

    def delete(self, cls: Type[T], name: str) -> None:
        return self._call("delete", self._inner.delete, cls, name)

    # ------------------------------------------------------------------
    # watches + plumbing: NEVER gated — the informer's watch reconnect is
    # how reads stay warm through the outage.
    # ------------------------------------------------------------------
    def watch(self, kind=None):
        return self._inner.watch(kind)

    def stop_watch(self, q) -> None:
        return self._inner.stop_watch(q)

    def keys(self):
        return self._inner.keys()

    def __len__(self) -> int:
        return len(self._inner)

    def __getattr__(self, name: str):
        inner = self.__dict__.get("_inner")
        if inner is None:
            raise AttributeError(name)
        return getattr(inner, name)

    # ------------------------------------------------------------------
    def snapshot(self) -> dict:
        """The /debug/storebreaker payload."""
        with self._lock:
            now = self._clock()
            return {
                "state": self._state,
                "trips": self.trips,
                "failure_streak": self._failures,
                "failure_threshold": self.failure_threshold,
                "reset_timeout_s": self.reset_timeout,
                "open_for_s": (
                    round(now - self._opened_at, 3)
                    if self._opened_at is not None else None
                ),
                "outage_seconds_total": round(
                    store_outage_seconds_total.total(), 3
                ),
                "resync_rate_per_s": self.resync_rate,
                "resync_window_s": self.resync_window,
                "pacing_active": now < self._pacing_until,
                "resyncs_paced_total": round(resync_paced_total.total()),
            }
