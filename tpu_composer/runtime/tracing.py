"""Causal tracing for the control plane — spans over reconciles, fabric
calls and agent actuation, connected across threads by explicit
``TraceContext`` propagation, exported as Chrome trace-event JSON.

The reference has NO tracing at all (SURVEY.md §5: no pprof, no otel — its
only observability is logs plus default metrics), which makes attach-path
latency regressions archaeology. The original subsystem here recorded
thread-local spans only; the moment an attach crossed a thread boundary
(queue -> reconcile worker -> dispatcher lane -> completion latch ->
requeue, or a restart + adoption pass) causality was lost. This version
makes the causality explicit:

- ``span(name, **attrs)``: context manager recording wall-time begin/end
  with attributes; spans nest via a thread-local stack, so a reconcile's
  fabric call shows up as a child of the reconcile span.
- ``TraceContext``: a (trace_id, flow) pair handed across thread
  boundaries. ``ctx.handoff()`` emits a Chrome *flow-start* event bound to
  the current span; opening a span with ``ctx=...`` (or calling
  ``link(ctx)`` inside one) emits the matching *flow-finish* — Perfetto
  draws an arrow from the producing span to the consuming one, across
  threads. The trace_id for a fabric op IS the durable
  ``status.pending_op`` nonce, so one attach renders as one connected
  trace even across a process crash + adoption (the kill–restart soak
  asserts this).
- A bounded in-memory ring (default 10k events — old traffic falls off
  rather than growing the heap) shared process-wide.
- ``export_chrome()``: the whole ring as Chrome trace-event JSON ("cat"
  = component, thread = worker) — load it in chrome://tracing or Perfetto.
- The manager's health server exposes ``/debug/traces`` (same port as
  healthz; read-only, no secrets — attribute values are names/counts),
  with ``?cat=`` / ``?limit=`` filtering and a response-size cap.
- ``TPUC_TRACE_FILE``: write the ring to a file at manager stop — and,
  via the crash hooks runtime/lifecycle.py installs, at interpreter exit
  and on unhandled thread exceptions, so a wedged or killed-by-exception
  process still leaves a trace behind.
- ``TPUC_TRACE=0`` (or ``set_enabled(False)``): hard-disable recording —
  ``span`` degrades to a no-op yield; the perf-smoke gate asserts the
  enabled path stays within 5% of this on the 32-chip wave.

The workload side (JAX) keeps its own richer profiler: ``jax.profiler``
traces device execution; this module covers the operator half the device
profiler can't see.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
import zlib
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, Iterator, List, Optional, Tuple

_lock = threading.Lock()
_events: Deque[Dict[str, Any]] = deque(maxlen=10_000)
_tls = threading.local()
_t0 = time.perf_counter()
# Monotonically-increasing ids shared by spans and flows so Perfetto can
# pair nested spans and flow arrows cheaply.
_next_id = 0
_enabled = os.environ.get("TPUC_TRACE", "1") != "0"
# Span-end sinks (the flight recorder subscribes): called OUTSIDE the ring
# lock with the finished event dict; exceptions are swallowed — a broken
# sink must never take down a reconcile.
_sinks: List[Callable[[Dict[str, Any]], None]] = []

#: Flow events all share one (name, cat) pair — Chrome/Perfetto match
#: flow-start to flow-finish on (name, cat, id), and the ids are unique.
_FLOW_NAME = "causal"
_FLOW_CAT = "flow"

#: Fleet identity for the Chrome trace "pid" column. Real OS replicas get
#: distinct os.getpid() values for free; the REPLICA tagging exists so (a)
#: merged multi-replica traces carry human process names, and (b) in-proc
#: replicas (bench harnesses, the shard-failover soak) render as distinct
#: Perfetto processes even though they share one interpreter. The module
#: default covers a whole process (cmd/main sets it once); bind_thread
#: overrides per thread for in-proc multi-replica harnesses.
_replica_default: Optional[Tuple[str, int]] = None
_pid_names: Dict[int, str] = {}


def replica_pid(identity: str) -> int:
    """Stable pseudo-pid for a replica identity (crc32, PYTHONHASHSEED-
    independent like shard_for): the same replica gets the same trace pid
    across restarts, so multi-incarnation merges line up."""
    return 100_000 + zlib.crc32(identity.encode("utf-8")) % 800_000


def set_replica(identity: Optional[str]) -> None:
    """Tag every event this PROCESS records with ``identity`` as its trace
    pid (None restores plain os.getpid()). cmd/main calls this when the
    fleet plane is on."""
    global _replica_default
    if identity is None:
        _replica_default = None
        return
    pid = replica_pid(identity)
    _pid_names[pid] = identity
    _replica_default = (identity, pid)


def bind_thread(identity: str) -> None:
    """Tag events recorded by THIS thread with ``identity``'s trace pid —
    the in-proc multi-replica hook: each replica's manager binds its
    controller workers, dispatcher lanes and runnables, so one shared ring
    still renders as N Perfetto processes."""
    pid = replica_pid(identity)
    _pid_names[pid] = identity
    _tls.replica = (identity, pid)


def current_replica() -> Optional[str]:
    """The identity whose pid this thread's events carry (thread binding
    first, then the process default), or None when untagged."""
    bound = getattr(_tls, "replica", None) or _replica_default
    return bound[0] if bound else None


def _pid() -> int:
    bound = getattr(_tls, "replica", None) or _replica_default
    return bound[1] if bound else os.getpid()


def _now_us() -> float:
    return (time.perf_counter() - _t0) * 1e6


def _new_id() -> int:
    global _next_id
    with _lock:
        _next_id += 1
        return _next_id


def _tid() -> int:
    return threading.get_ident() % 1_000_000


@dataclass
class TraceContext:
    """A causal handle crossing a thread (or process-restart) boundary.

    ``trace_id`` groups every span of one logical operation — for fabric
    ops it is the durable ``status.pending_op`` nonce, which is what makes
    the trace survive a crash + adoption. ``flow_id`` is a one-shot Chrome
    flow-arrow id emitted by :meth:`handoff` on the producing thread and
    consumed by the first ``span(ctx=...)`` / ``link`` on the consumer.
    """

    trace_id: str
    flow_id: Optional[int] = None
    _flow_consumed: bool = field(default=False, repr=False)

    def handoff(self) -> "TraceContext":
        """Mint a context to hand to another thread: emits a flow-start
        bound to the CURRENT thread's enclosing span and returns a fresh
        context (same trace_id, new one-shot flow id)."""
        if not _enabled:
            return TraceContext(self.trace_id)
        fid = _new_id()
        evt = {
            "name": _FLOW_NAME, "cat": _FLOW_CAT, "ph": "s", "id": fid,
            "ts": _now_us(), "pid": _pid(), "tid": _tid(),
            "args": {"trace_id": self.trace_id},
        }
        with _lock:
            _events.append(evt)
        return TraceContext(self.trace_id, flow_id=fid)


def new_trace(trace_id: Optional[str] = None) -> TraceContext:
    return TraceContext(trace_id or uuid.uuid4().hex[:12])


def context() -> Optional[TraceContext]:
    """The thread's active TraceContext (None outside any trace)."""
    return getattr(_tls, "ctx", None)


def _consume_flow(ctx: TraceContext, ts: Optional[float] = None) -> None:
    if ctx.flow_id is None or ctx._flow_consumed:
        return
    ctx._flow_consumed = True
    evt = {
        "name": _FLOW_NAME, "cat": _FLOW_CAT, "ph": "f", "bp": "e",
        "id": ctx.flow_id, "ts": ts if ts is not None else _now_us(),
        "pid": _pid(), "tid": _tid(),
        "args": {"trace_id": ctx.trace_id},
    }
    with _lock:
        _events.append(evt)


def link(ctx: Optional[TraceContext]) -> None:
    """Consume ``ctx``'s pending flow inside the current span — draws the
    arrow from the producing span into this one WITHOUT making ctx the
    thread's active context (how a batched group call links each member's
    submission into the one parent dispatch span)."""
    if ctx is None or not _enabled:
        return
    _consume_flow(ctx)


def adopt_trace(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Make ``ctx`` the thread's active context and back-fill its trace_id
    into every currently-open span (the reconcile span is already open when
    the resource controller discovers the CR's pending_op nonce). Returns
    the previous context; the enclosing ``span()`` restores it on exit.

    Outside any open span the context is NOT made active — there would be
    no restore point, so it would leak onto the thread and stamp every
    later unrelated span (bit tests calling reconcile() directly, without
    the controller loop's wrapping span)."""
    prev = getattr(_tls, "ctx", None)
    stack = getattr(_tls, "stack", None)
    if stack:
        _tls.ctx = ctx
    if ctx is not None and _enabled:
        for _, args in stack or ():
            args["trace_id"] = ctx.trace_id
        _consume_flow(ctx)
    return prev


def set_enabled(on: bool) -> None:
    """Hard on/off switch (TPUC_TRACE=0). Disabled: spans yield without
    recording, handoffs carry trace ids but emit nothing."""
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


def configure(capacity: int) -> None:
    """Resize the ring (drops current contents). Safe during active spans:
    in-flight spans append into whichever ring is current at their end."""
    global _events
    with _lock:
        _events = deque(maxlen=capacity)


def reset() -> None:
    with _lock:
        _events.clear()


def add_span_sink(fn: Callable[[Dict[str, Any]], None]) -> None:
    if fn not in _sinks:
        _sinks.append(fn)


def remove_span_sink(fn: Callable[[Dict[str, Any]], None]) -> None:
    if fn in _sinks:
        _sinks.remove(fn)


@contextmanager
def span(
    name: str, cat: str = "operator", ctx: Optional[TraceContext] = None,
    **attrs: Any,
) -> Iterator[Dict[str, Any]]:
    """Record one complete span. Yields the attribute dict so callers can
    attach results discovered mid-span (e.g. outcome="requeued").

    ``ctx`` joins the span to a propagated trace: its trace_id lands in the
    span's args, its pending flow (if any) is consumed here — drawing the
    cross-thread arrow into this span — and it becomes the thread's active
    context for the span's duration, so child spans (and handoffs made
    inside) inherit the trace."""
    if not _enabled:
        yield dict(attrs)
        return
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    sid = _new_id()
    parent = _tls.stack[-1][0] if _tls.stack else None
    prev_ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        _tls.ctx = ctx
    active = getattr(_tls, "ctx", None)
    args: Dict[str, Any] = dict(attrs)
    if parent is not None:
        args["parent_span"] = parent
    if active is not None and active.trace_id:
        args["trace_id"] = active.trace_id
    _tls.stack.append((sid, args))
    begin = _now_us()
    if ctx is not None:
        _consume_flow(ctx, begin)
    try:
        yield args
    except BaseException as e:
        args["error"] = f"{type(e).__name__}: {e}"
        raise
    finally:
        _tls.stack.pop()
        _tls.ctx = prev_ctx
        end = _now_us()
        evt = {
            "name": name,
            "cat": cat,
            "ph": "X",  # complete event
            "ts": begin,
            "dur": end - begin,
            "pid": _pid(),
            "tid": _tid(),
            "id": sid,
            "args": {k: _safe(v) for k, v in args.items()},
        }
        with _lock:
            _events.append(evt)
        for sink in list(_sinks):
            try:
                sink(evt)
            except Exception:
                pass  # a sink bug must never surface into the traced code


def _safe(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def snapshot(
    cat: Optional[str] = None, limit: Optional[int] = None
) -> List[Dict[str, Any]]:
    """The ring's events, oldest first; ``cat`` filters by category and
    ``limit`` keeps only the NEWEST n (what /debug/traces paginates on)."""
    with _lock:
        events = list(_events)
    if cat:
        events = [e for e in events if e.get("cat") == cat]
    if limit is not None and limit >= 0:
        # NB: events[-0:] would be the FULL list — limit=0 means none.
        events = events[-limit:] if limit else []
    return events


def _process_name_events(events: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Chrome metadata events naming each known replica pid present in
    ``events`` — Perfetto's process rail shows the identity, not a number."""
    pids = {e.get("pid") for e in events}
    return [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": name}}
        for pid, name in sorted(_pid_names.items())
        if pid in pids
    ]


def chrome_doc(events: Optional[List[Dict[str, Any]]] = None) -> Dict[str, Any]:
    """The Chrome JSON-Object trace document for ``events`` (default: the
    ring), carrying the two merge anchors the ring events themselves lack:
    ``process_name`` metadata events for every replica-tagged pid, and a
    top-level ``metadata.epoch_us`` (the wall-clock instant of ts=0) so
    :func:`merge_chrome` can align files from processes whose monotonic
    trace clocks started at different moments. Every trace exit path —
    file dumps, /debug/traces scrapes, pre-kill snapshots — must ship this
    shape or its events merge unlabeled and unaligned."""
    if events is None:
        events = snapshot()
    epoch_us = time.time() * 1e6 - _now_us()
    return {
        "traceEvents": _process_name_events(events) + events,
        "displayTimeUnit": "ms",
        "metadata": {"epoch_us": epoch_us},
    }


def export_chrome(events: Optional[List[Dict[str, Any]]] = None) -> str:
    """Chrome trace-event format (the JSON Object flavor) — open in
    chrome://tracing or https://ui.perfetto.dev. Flow events ("ph": s/f)
    render as arrows connecting spans across threads."""
    return json.dumps(chrome_doc(events))


def write_file(path: Optional[str] = None) -> Optional[str]:
    """Dump the ring to ``path`` (default $TPUC_TRACE_FILE); returns the
    path written or None when tracing-to-file is not configured. Called at
    clean manager stop, on drain-timeout, and by the lifecycle crash hooks
    (atexit / unhandled thread exception)."""
    path = path or os.environ.get("TPUC_TRACE_FILE")
    if not path:
        return None
    with open(path, "w") as f:
        f.write(export_chrome())
    return path


def summarize(cat: Optional[str] = None) -> Dict[str, Dict[str, float]]:
    """Per-span-name count/total/max durations (ms) — the quick look that
    answers 'where did the attach time go' without leaving the terminal."""
    out: Dict[str, Dict[str, float]] = {}
    for e in snapshot():
        if e.get("ph") != "X":
            continue  # flow events carry no duration
        if cat and e["cat"] != cat:
            continue
        s = out.setdefault(e["name"], {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
        dur_ms = e["dur"] / 1e3
        s["count"] += 1
        s["total_ms"] += dur_ms
        s["max_ms"] = max(s["max_ms"], dur_ms)
    return out


def trace_events(trace_id: str) -> List[Dict[str, Any]]:
    """Every ring event belonging to one trace (spans + flow arrows)."""
    return [
        e for e in snapshot()
        if e.get("args", {}).get("trace_id") == trace_id
    ]


# ----------------------------------------------------------------------
# cross-process trace merging (the fleet observatory's stitch pass)
# ----------------------------------------------------------------------
def merge_chrome(docs: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-replica Chrome trace documents into ONE stitched trace.

    Three passes make a kill -9 failover render as a single connected
    Perfetto story instead of N unrelated fragments:

    1. **Clock alignment.** Each document's ``metadata.epoch_us`` (the
       wall instant of its ts=0) shifts its events onto one shared
       timeline — two processes' monotonic trace clocks start at
       different moments, and unshifted spans would interleave nonsense.
       Documents without the anchor (pre-fleet exports) merge unshifted.
    2. **Pid disambiguation.** Documents whose events collide on a pid
       (two unrelated hosts can reuse an OS pid) get the later file's
       colliding pids remapped to a free range; replica-tagged pseudo-pids
       (:func:`replica_pid`) are already collision-managed and keep their
       values, so process_name metadata stays attached.
    3. **Flow stitching.** Span events sharing one ``args.trace_id`` (the
       durable intent nonce) across DIFFERENT pids get synthetic flow
       start/finish pairs connecting each cross-pid neighbor in time order
       — the arrow from replica A's pre-crash attach span to replica B's
       post-crash adopt span that no single process could have emitted.
       Stitched flows carry ``args.stitched = true`` so a reader can tell
       reconstructed causality from recorded causality.
    """
    for doc in docs:
        if not isinstance(doc, dict):
            raise ValueError(
                "trace document is not a JSON object — only the Chrome"
                " JSON-Object flavor ({'traceEvents': [...]}) merges"
            )
    merged: List[Dict[str, Any]] = []
    epochs = [
        float((d.get("metadata") or {}).get("epoch_us") or 0.0) for d in docs
    ]
    known = [e for e in epochs if e > 0]
    base = min(known) if known else 0.0
    used_pids: set = set()
    # pid -> process_name already merged under that pid. A colliding pid
    # is kept only when both files NAME it identically (two incarnations
    # of one replica — replica_pid is stable across restarts exactly so
    # their files line up); unnamed or differently-named collisions are
    # remapped. Read from the DOCUMENTS' own metadata, never from this
    # process's registry — the trace-merge CLI runs in a process that
    # recorded nothing.
    pid_owner: Dict[int, str] = {}
    used_ids: set = set()
    max_id = 0
    for doc, epoch in zip(docs, epochs):
        events = [dict(e) for e in doc.get("traceEvents", [])]
        shift = (epoch - base) if epoch > 0 else 0.0
        doc_pids = {e.get("pid") for e in events if "pid" in e}
        doc_names = {
            e["pid"]: e["args"]["name"]
            for e in events
            if e.get("name") == "process_name"
            and isinstance(e.get("args"), dict) and "name" in e["args"]
        }
        remap: Dict[int, int] = {}
        for pid in sorted(p for p in doc_pids if isinstance(p, int)):
            if pid not in used_pids:
                continue
            name = doc_names.get(pid, "")
            if name and pid_owner.get(pid) == name:
                continue  # same replica identity — same Perfetto process
            new = pid
            while new in used_pids:
                new += 1_000_000
            remap[pid] = new
        # Event ids restart at 0 in every process, so every file reuses
        # flow ids 1..N under the one (cat, name) flow key — colliding
        # ids from a later file must be remapped or Perfetto binds
        # causally unrelated flows across replicas. One mapping per file
        # keeps its own s/f pairs intact; replacement ids dodge both the
        # already-merged ids and this file's own (a replacement equal to
        # a later id in the same file would be a fresh collision).
        doc_ids = {
            e["id"] for e in events if isinstance(e.get("id"), int)
        }
        id_remap: Dict[int, int] = {}
        for e in events:
            if shift and "ts" in e:
                e["ts"] = e["ts"] + shift
            if e.get("pid") in remap:
                e["pid"] = remap[e["pid"]]
            eid = e.get("id")
            if isinstance(eid, int):
                if eid in id_remap:
                    e["id"] = id_remap[eid]
                elif eid in used_ids:
                    max_id += 1
                    while max_id in used_ids or max_id in doc_ids:
                        max_id += 1
                    id_remap[eid] = max_id
                    e["id"] = max_id
                max_id = max(max_id, e["id"])
        used_ids.update(
            e["id"] for e in events if isinstance(e.get("id"), int)
        )
        used_pids.update(e.get("pid") for e in events if "pid" in e)
        for pid, name in doc_names.items():
            pid_owner.setdefault(remap.get(pid, pid), name)
        merged.extend(events)

    # Stitch: one synthetic flow per cross-pid neighbor pair per trace id.
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for e in merged:
        if e.get("ph") != "X":
            continue
        tid = (e.get("args") or {}).get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(e)
    stitches: List[Dict[str, Any]] = []
    next_id = max_id + 1
    for trace_id, spans in by_trace.items():
        if len({s["pid"] for s in spans}) < 2:
            continue
        spans.sort(key=lambda s: s.get("ts", 0.0))
        for a, b in zip(spans, spans[1:]):
            if a["pid"] == b["pid"]:
                continue
            args = {"trace_id": trace_id, "stitched": True}
            stitches.append({
                "name": _FLOW_NAME, "cat": _FLOW_CAT, "ph": "s",
                "id": next_id, "ts": a["ts"] + a.get("dur", 0.0),
                "pid": a["pid"], "tid": a.get("tid", 0), "args": dict(args),
            })
            stitches.append({
                "name": _FLOW_NAME, "cat": _FLOW_CAT, "ph": "f", "bp": "e",
                "id": next_id, "ts": b["ts"],
                "pid": b["pid"], "tid": b.get("tid", 0), "args": dict(args),
            })
            next_id += 1
    merged.extend(stitches)
    return {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "metadata": {
            "epoch_us": base,
            "merged_files": len(docs),
            "stitched_flows": len(stitches) // 2,
        },
    }


def merge_files(paths: List[str]) -> Dict[str, Any]:
    """Load per-replica trace files (``write_file`` / crash-hook output)
    and return the stitched merge — the ``tpu-composer trace-merge``
    subcommand's engine."""
    docs = []
    for path in paths:
        with open(path) as f:
            docs.append(json.load(f))
    return merge_chrome(docs)
