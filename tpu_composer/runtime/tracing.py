"""Causal tracing for the control plane — spans over reconciles, fabric
calls and agent actuation, connected across threads by explicit
``TraceContext`` propagation, exported as Chrome trace-event JSON.

The reference has NO tracing at all (SURVEY.md §5: no pprof, no otel — its
only observability is logs plus default metrics), which makes attach-path
latency regressions archaeology. The original subsystem here recorded
thread-local spans only; the moment an attach crossed a thread boundary
(queue -> reconcile worker -> dispatcher lane -> completion latch ->
requeue, or a restart + adoption pass) causality was lost. This version
makes the causality explicit:

- ``span(name, **attrs)``: context manager recording wall-time begin/end
  with attributes; spans nest via a thread-local stack, so a reconcile's
  fabric call shows up as a child of the reconcile span.
- ``TraceContext``: a (trace_id, flow) pair handed across thread
  boundaries. ``ctx.handoff()`` emits a Chrome *flow-start* event bound to
  the current span; opening a span with ``ctx=...`` (or calling
  ``link(ctx)`` inside one) emits the matching *flow-finish* — Perfetto
  draws an arrow from the producing span to the consuming one, across
  threads. The trace_id for a fabric op IS the durable
  ``status.pending_op`` nonce, so one attach renders as one connected
  trace even across a process crash + adoption (the kill–restart soak
  asserts this).
- A bounded in-memory ring (default 10k events — old traffic falls off
  rather than growing the heap) shared process-wide.
- ``export_chrome()``: the whole ring as Chrome trace-event JSON ("cat"
  = component, thread = worker) — load it in chrome://tracing or Perfetto.
- The manager's health server exposes ``/debug/traces`` (same port as
  healthz; read-only, no secrets — attribute values are names/counts),
  with ``?cat=`` / ``?limit=`` filtering and a response-size cap.
- ``TPUC_TRACE_FILE``: write the ring to a file at manager stop — and,
  via the crash hooks runtime/lifecycle.py installs, at interpreter exit
  and on unhandled thread exceptions, so a wedged or killed-by-exception
  process still leaves a trace behind.
- ``TPUC_TRACE=0`` (or ``set_enabled(False)``): hard-disable recording —
  ``span`` degrades to a no-op yield; the perf-smoke gate asserts the
  enabled path stays within 5% of this on the 32-chip wave.

The workload side (JAX) keeps its own richer profiler: ``jax.profiler``
traces device execution; this module covers the operator half the device
profiler can't see.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, Iterator, List, Optional, Tuple

_lock = threading.Lock()
_events: Deque[Dict[str, Any]] = deque(maxlen=10_000)
_tls = threading.local()
_t0 = time.perf_counter()
# Monotonically-increasing ids shared by spans and flows so Perfetto can
# pair nested spans and flow arrows cheaply.
_next_id = 0
_enabled = os.environ.get("TPUC_TRACE", "1") != "0"
# Span-end sinks (the flight recorder subscribes): called OUTSIDE the ring
# lock with the finished event dict; exceptions are swallowed — a broken
# sink must never take down a reconcile.
_sinks: List[Callable[[Dict[str, Any]], None]] = []

#: Flow events all share one (name, cat) pair — Chrome/Perfetto match
#: flow-start to flow-finish on (name, cat, id), and the ids are unique.
_FLOW_NAME = "causal"
_FLOW_CAT = "flow"


def _now_us() -> float:
    return (time.perf_counter() - _t0) * 1e6


def _new_id() -> int:
    global _next_id
    with _lock:
        _next_id += 1
        return _next_id


def _tid() -> int:
    return threading.get_ident() % 1_000_000


@dataclass
class TraceContext:
    """A causal handle crossing a thread (or process-restart) boundary.

    ``trace_id`` groups every span of one logical operation — for fabric
    ops it is the durable ``status.pending_op`` nonce, which is what makes
    the trace survive a crash + adoption. ``flow_id`` is a one-shot Chrome
    flow-arrow id emitted by :meth:`handoff` on the producing thread and
    consumed by the first ``span(ctx=...)`` / ``link`` on the consumer.
    """

    trace_id: str
    flow_id: Optional[int] = None
    _flow_consumed: bool = field(default=False, repr=False)

    def handoff(self) -> "TraceContext":
        """Mint a context to hand to another thread: emits a flow-start
        bound to the CURRENT thread's enclosing span and returns a fresh
        context (same trace_id, new one-shot flow id)."""
        if not _enabled:
            return TraceContext(self.trace_id)
        fid = _new_id()
        evt = {
            "name": _FLOW_NAME, "cat": _FLOW_CAT, "ph": "s", "id": fid,
            "ts": _now_us(), "pid": os.getpid(), "tid": _tid(),
            "args": {"trace_id": self.trace_id},
        }
        with _lock:
            _events.append(evt)
        return TraceContext(self.trace_id, flow_id=fid)


def new_trace(trace_id: Optional[str] = None) -> TraceContext:
    return TraceContext(trace_id or uuid.uuid4().hex[:12])


def context() -> Optional[TraceContext]:
    """The thread's active TraceContext (None outside any trace)."""
    return getattr(_tls, "ctx", None)


def _consume_flow(ctx: TraceContext, ts: Optional[float] = None) -> None:
    if ctx.flow_id is None or ctx._flow_consumed:
        return
    ctx._flow_consumed = True
    evt = {
        "name": _FLOW_NAME, "cat": _FLOW_CAT, "ph": "f", "bp": "e",
        "id": ctx.flow_id, "ts": ts if ts is not None else _now_us(),
        "pid": os.getpid(), "tid": _tid(),
        "args": {"trace_id": ctx.trace_id},
    }
    with _lock:
        _events.append(evt)


def link(ctx: Optional[TraceContext]) -> None:
    """Consume ``ctx``'s pending flow inside the current span — draws the
    arrow from the producing span into this one WITHOUT making ctx the
    thread's active context (how a batched group call links each member's
    submission into the one parent dispatch span)."""
    if ctx is None or not _enabled:
        return
    _consume_flow(ctx)


def adopt_trace(ctx: Optional[TraceContext]) -> Optional[TraceContext]:
    """Make ``ctx`` the thread's active context and back-fill its trace_id
    into every currently-open span (the reconcile span is already open when
    the resource controller discovers the CR's pending_op nonce). Returns
    the previous context; the enclosing ``span()`` restores it on exit.

    Outside any open span the context is NOT made active — there would be
    no restore point, so it would leak onto the thread and stamp every
    later unrelated span (bit tests calling reconcile() directly, without
    the controller loop's wrapping span)."""
    prev = getattr(_tls, "ctx", None)
    stack = getattr(_tls, "stack", None)
    if stack:
        _tls.ctx = ctx
    if ctx is not None and _enabled:
        for _, args in stack or ():
            args["trace_id"] = ctx.trace_id
        _consume_flow(ctx)
    return prev


def set_enabled(on: bool) -> None:
    """Hard on/off switch (TPUC_TRACE=0). Disabled: spans yield without
    recording, handoffs carry trace ids but emit nothing."""
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


def configure(capacity: int) -> None:
    """Resize the ring (drops current contents). Safe during active spans:
    in-flight spans append into whichever ring is current at their end."""
    global _events
    with _lock:
        _events = deque(maxlen=capacity)


def reset() -> None:
    with _lock:
        _events.clear()


def add_span_sink(fn: Callable[[Dict[str, Any]], None]) -> None:
    if fn not in _sinks:
        _sinks.append(fn)


def remove_span_sink(fn: Callable[[Dict[str, Any]], None]) -> None:
    if fn in _sinks:
        _sinks.remove(fn)


@contextmanager
def span(
    name: str, cat: str = "operator", ctx: Optional[TraceContext] = None,
    **attrs: Any,
) -> Iterator[Dict[str, Any]]:
    """Record one complete span. Yields the attribute dict so callers can
    attach results discovered mid-span (e.g. outcome="requeued").

    ``ctx`` joins the span to a propagated trace: its trace_id lands in the
    span's args, its pending flow (if any) is consumed here — drawing the
    cross-thread arrow into this span — and it becomes the thread's active
    context for the span's duration, so child spans (and handoffs made
    inside) inherit the trace."""
    if not _enabled:
        yield dict(attrs)
        return
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    sid = _new_id()
    parent = _tls.stack[-1][0] if _tls.stack else None
    prev_ctx = getattr(_tls, "ctx", None)
    if ctx is not None:
        _tls.ctx = ctx
    active = getattr(_tls, "ctx", None)
    args: Dict[str, Any] = dict(attrs)
    if parent is not None:
        args["parent_span"] = parent
    if active is not None and active.trace_id:
        args["trace_id"] = active.trace_id
    _tls.stack.append((sid, args))
    begin = _now_us()
    if ctx is not None:
        _consume_flow(ctx, begin)
    try:
        yield args
    except BaseException as e:
        args["error"] = f"{type(e).__name__}: {e}"
        raise
    finally:
        _tls.stack.pop()
        _tls.ctx = prev_ctx
        end = _now_us()
        evt = {
            "name": name,
            "cat": cat,
            "ph": "X",  # complete event
            "ts": begin,
            "dur": end - begin,
            "pid": os.getpid(),
            "tid": _tid(),
            "id": sid,
            "args": {k: _safe(v) for k, v in args.items()},
        }
        with _lock:
            _events.append(evt)
        for sink in list(_sinks):
            try:
                sink(evt)
            except Exception:
                pass  # a sink bug must never surface into the traced code


def _safe(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def snapshot(
    cat: Optional[str] = None, limit: Optional[int] = None
) -> List[Dict[str, Any]]:
    """The ring's events, oldest first; ``cat`` filters by category and
    ``limit`` keeps only the NEWEST n (what /debug/traces paginates on)."""
    with _lock:
        events = list(_events)
    if cat:
        events = [e for e in events if e.get("cat") == cat]
    if limit is not None and limit >= 0:
        # NB: events[-0:] would be the FULL list — limit=0 means none.
        events = events[-limit:] if limit else []
    return events


def export_chrome(events: Optional[List[Dict[str, Any]]] = None) -> str:
    """Chrome trace-event format (the JSON Object flavor) — open in
    chrome://tracing or https://ui.perfetto.dev. Flow events ("ph": s/f)
    render as arrows connecting spans across threads."""
    if events is None:
        events = snapshot()
    return json.dumps({"traceEvents": events, "displayTimeUnit": "ms"})


def write_file(path: Optional[str] = None) -> Optional[str]:
    """Dump the ring to ``path`` (default $TPUC_TRACE_FILE); returns the
    path written or None when tracing-to-file is not configured. Called at
    clean manager stop, on drain-timeout, and by the lifecycle crash hooks
    (atexit / unhandled thread exception)."""
    path = path or os.environ.get("TPUC_TRACE_FILE")
    if not path:
        return None
    with open(path, "w") as f:
        f.write(export_chrome())
    return path


def summarize(cat: Optional[str] = None) -> Dict[str, Dict[str, float]]:
    """Per-span-name count/total/max durations (ms) — the quick look that
    answers 'where did the attach time go' without leaving the terminal."""
    out: Dict[str, Dict[str, float]] = {}
    for e in snapshot():
        if e.get("ph") != "X":
            continue  # flow events carry no duration
        if cat and e["cat"] != cat:
            continue
        s = out.setdefault(e["name"], {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
        dur_ms = e["dur"] / 1e3
        s["count"] += 1
        s["total_ms"] += dur_ms
        s["max_ms"] = max(s["max_ms"], dur_ms)
    return out


def trace_events(trace_id: str) -> List[Dict[str, Any]]:
    """Every ring event belonging to one trace (spans + flow arrows)."""
    return [
        e for e in snapshot()
        if e.get("args", {}).get("trace_id") == trace_id
    ]
