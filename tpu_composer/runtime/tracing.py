"""Lightweight tracing for the control plane — spans over reconciles,
fabric calls and agent actuation, exported as Chrome trace-event JSON.

The reference has NO tracing at all (SURVEY.md §5: no pprof, no otel — its
only observability is logs plus default metrics), which makes attach-path
latency regressions archaeology. This subsystem exceeds that bar with ~150
lines and zero dependencies:

- ``span(name, **attrs)``: context manager recording wall-time begin/end
  with attributes; spans nest via a thread-local stack, so a reconcile's
  fabric call shows up as a child of the reconcile span.
- A bounded in-memory ring (default 10k events — old traffic falls off
  rather than growing the heap) shared process-wide.
- ``export_chrome()``: the whole ring as Chrome trace-event JSON ("cat"
  = component, thread = worker) — load it in chrome://tracing or Perfetto.
- The manager's health server exposes ``/debug/traces`` (same port as
  healthz; read-only, no secrets — attribute values are names/counts).
- ``TPUC_TRACE_FILE``: write the ring to a file at manager stop, for
  headless runs.

The workload side (JAX) keeps its own richer profiler: ``jax.profiler``
traces device execution; this module covers the operator half the device
profiler can't see.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Any, Deque, Dict, Iterator, List, Optional

_lock = threading.Lock()
_events: Deque[Dict[str, Any]] = deque(maxlen=10_000)
_tls = threading.local()
_t0 = time.perf_counter()
# Monotonically-increasing ids so Perfetto can pair nested spans cheaply.
_next_id = 0


def _now_us() -> float:
    return (time.perf_counter() - _t0) * 1e6


def configure(capacity: int) -> None:
    """Resize the ring (drops current contents)."""
    global _events
    with _lock:
        _events = deque(maxlen=capacity)


def reset() -> None:
    with _lock:
        _events.clear()


def _depth() -> int:
    return len(getattr(_tls, "stack", ()))


@contextmanager
def span(name: str, cat: str = "operator", **attrs: Any) -> Iterator[Dict[str, Any]]:
    """Record one complete span. Yields the attribute dict so callers can
    attach results discovered mid-span (e.g. outcome="requeued")."""
    global _next_id
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    with _lock:
        _next_id += 1
        sid = _next_id
    parent = _tls.stack[-1] if _tls.stack else None
    _tls.stack.append(sid)
    args: Dict[str, Any] = dict(attrs)
    if parent is not None:
        args["parent_span"] = parent
    begin = _now_us()
    try:
        yield args
    except BaseException as e:
        args["error"] = f"{type(e).__name__}: {e}"
        raise
    finally:
        _tls.stack.pop()
        end = _now_us()
        evt = {
            "name": name,
            "cat": cat,
            "ph": "X",  # complete event
            "ts": begin,
            "dur": end - begin,
            "pid": os.getpid(),
            "tid": threading.get_ident() % 1_000_000,
            "id": sid,
            "args": {k: _safe(v) for k, v in args.items()},
        }
        with _lock:
            _events.append(evt)


def _safe(v: Any) -> Any:
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


def snapshot() -> List[Dict[str, Any]]:
    with _lock:
        return list(_events)


def export_chrome() -> str:
    """Chrome trace-event format (the JSON Array flavor) — open in
    chrome://tracing or https://ui.perfetto.dev."""
    return json.dumps({"traceEvents": snapshot(), "displayTimeUnit": "ms"})


def write_file(path: Optional[str] = None) -> Optional[str]:
    """Dump the ring to ``path`` (default $TPUC_TRACE_FILE); returns the
    path written or None when tracing-to-file is not configured."""
    path = path or os.environ.get("TPUC_TRACE_FILE")
    if not path:
        return None
    with open(path, "w") as f:
        f.write(export_chrome())
    return path


def summarize(cat: Optional[str] = None) -> Dict[str, Dict[str, float]]:
    """Per-span-name count/total/max durations (ms) — the quick look that
    answers 'where did the attach time go' without leaving the terminal."""
    out: Dict[str, Dict[str, float]] = {}
    for e in snapshot():
        if cat and e["cat"] != cat:
            continue
        s = out.setdefault(e["name"], {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
        dur_ms = e["dur"] / 1e3
        s["count"] += 1
        s["total_ms"] += dur_ms
        s["max_ms"] = max(s["max_ms"], dur_ms)
    return out
