"""Subsystem watchdog: heartbeat registry + stall detection + restarts.

Fourteen PRs of machinery run as named daemon threads (manager runnables,
controller workers, dispatcher lanes — the PR 13 named-threads pass
guarantees every one is attributable), and until now a wedged one stalled
SILENTLY until an SLO burned minutes later. The watchdog closes that gap:

- subsystems ``beat(name)`` from inside their loops (controller workers
  beat every queue.get() wake, ≥5x/s healthy; dispatcher lanes every cond
  wake; the overload governor every tick). First beat auto-registers with
  the default stall threshold; loops that legitimately run slower
  register explicitly with their own ``stall_after``.
- the scan loop (a Manager runnable) flags a subsystem whose last beat is
  older than its threshold ONCE per stall edge (the flag re-arms when a
  fresh beat lands): ``tpuc_watchdog_stalls_total{subsystem}``, a
  ``WatchdogStall`` Event, a flight-recorder entry, and an on-demand
  profiler burst capturing the wedged stack (``profile_burst`` works even
  with TPUC_PROFILE=0 — the one-shot sampler needs no resident thread).
- a subsystem registered ``restartable`` is restarted through the
  Manager's respawn hook, bounded by ``restart_budget`` per subsystem
  (``tpuc_watchdog_restarts_total{subsystem}``); a stall past the budget
  — or the third stall of any subsystem — dumps the black boxes
  (flight/trace/profile/SLO/fleet/decisions) via ``lifecycle.dump_crash``
  so the evidence survives even if the process is later killed.

False-positive discipline: the threshold is per-subsystem and the beat
sits at the top of each loop iteration, so a slow-but-progressing loop (a
GC pause, a long store RTT inside one reconcile) never trips as long as
one iteration completes per window. Exiting loops ``unregister`` so a
clean shutdown can't race the final scan into a phantom stall.

Wired by cmd/main (``--watchdog`` / ``TPUC_WATCHDOG``, default on; =0
constructs none of this). ``/debug/watchdog`` serves :meth:`snapshot`.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable, Dict, Optional

from tpu_composer.runtime import lifecycle
from tpu_composer.runtime.metrics import (
    watchdog_restarts_total,
    watchdog_stalls_total,
)

log = logging.getLogger("tpuc.watchdog")

#: Stalls of one subsystem after which the black boxes are dumped even if
#: restarts are still inside the budget — repeated stalls mean the restart
#: is not fixing it and the evidence should hit disk now.
_DUMP_AFTER_STALLS = 3


class _Subsystem:
    __slots__ = (
        "name", "stall_after", "restartable", "restart",
        "last_beat", "stalled", "stalls", "restarts", "beats",
    )

    def __init__(self, name: str, stall_after: float, now: float,
                 restartable: bool, restart: Optional[Callable[[], bool]]):
        self.name = name
        self.stall_after = stall_after
        self.restartable = restartable
        self.restart = restart
        self.last_beat = now
        self.stalled = False
        self.stalls = 0
        self.restarts = 0
        self.beats = 0


class Watchdog:
    def __init__(
        self,
        stall_after: float = 30.0,
        restart_budget: int = 3,
        scan_period: Optional[float] = None,
        capture_burst: bool = True,
        recorder=None,   # duck-typed EventRecorder (.event)
        clock: Callable[[], float] = time.monotonic,
        burst_seconds: float = 0.5,
    ) -> None:
        self.stall_after = stall_after
        self.restart_budget = max(0, restart_budget)
        self.scan_period = scan_period or max(0.2, stall_after / 4.0)
        self.capture_burst = capture_burst
        self.recorder = recorder
        self.burst_seconds = burst_seconds
        self._clock = clock
        self._lock = threading.Lock()
        self._subsystems: Dict[str, _Subsystem] = {}
        #: Manager's respawn hook for restartable runnables without their
        #: own restart callable (set by Manager.start()).
        self.restarter: Optional[Callable[[str], bool]] = None
        #: last stall's profiler-burst top frames, for /debug/watchdog.
        self._last_burst: Optional[Dict[str, Any]] = None
        self._dumped: set = set()

    # ------------------------------------------------------------------
    # registry
    # ------------------------------------------------------------------
    def register(
        self,
        name: str,
        stall_after: Optional[float] = None,
        restartable: bool = False,
        restart: Optional[Callable[[], bool]] = None,
    ) -> None:
        """Start monitoring ``name``. ``restart`` (or, for a Manager
        runnable, the Manager's respawn hook) is invoked on stall while
        the restart budget lasts."""
        now = self._clock()
        with self._lock:
            self._subsystems[name] = _Subsystem(
                name, stall_after or self.stall_after, now,
                restartable or restart is not None, restart,
            )

    def unregister(self, name: str) -> None:
        with self._lock:
            self._subsystems.pop(name, None)

    def beat(self, name: str) -> None:
        """Record liveness. Unknown names auto-register with defaults so
        worker loops need no setup call."""
        now = self._clock()
        with self._lock:
            sub = self._subsystems.get(name)
            if sub is None:
                sub = _Subsystem(name, self.stall_after, now, False, None)
                self._subsystems[name] = sub
            sub.last_beat = now
            sub.beats += 1
            if sub.stalled:
                sub.stalled = False  # recovered — re-arm the edge
                log.info("watchdog: %s recovered (beat after stall)", name)

    # ------------------------------------------------------------------
    # detection
    # ------------------------------------------------------------------
    def scan(self, now: Optional[float] = None) -> int:
        """One detection pass; returns the number of NEW stalls flagged.
        ``now`` is injectable for deterministic tests."""
        now = self._clock() if now is None else now
        stalled: list = []
        with self._lock:
            for sub in self._subsystems.values():
                if sub.stalled:
                    continue
                if now - sub.last_beat > sub.stall_after:
                    sub.stalled = True
                    sub.stalls += 1
                    stalled.append(sub)
        for sub in stalled:
            self._handle_stall(sub, now)
        return len(stalled)

    def _handle_stall(self, sub: _Subsystem, now: float) -> None:
        age = now - sub.last_beat
        msg = (
            f"subsystem {sub.name} stalled: no heartbeat for {age:.1f}s"
            f" (threshold {sub.stall_after:.1f}s, stall #{sub.stalls})"
        )
        log.error("watchdog: %s", msg)
        watchdog_stalls_total.inc(subsystem=sub.name)
        lifecycle.recorder.note_event(
            "Watchdog", sub.name, "Warning", "WatchdogStall", msg
        )
        if self.recorder is not None:
            try:
                self.recorder.event(
                    _WatchdogRef(sub.name), "Warning", "WatchdogStall", msg
                )
            except Exception:
                log.exception("watchdog: stall event failed")
        # Capture the wedged stack NOW: a one-shot burst on this thread,
        # independent of the always-on sampler (works under TPUC_PROFILE=0).
        if self.capture_burst:
            try:
                from tpu_composer.runtime import profiler as profiler_mod

                burst = profiler_mod.profile_burst(
                    seconds=self.burst_seconds, interval=0.02
                )
                self._last_burst = {
                    "subsystem": sub.name,
                    "at_mono": round(now, 3),
                    "top": burst.top(10),
                }
            except Exception:
                log.exception("watchdog: profiler burst failed")
        restarted = False
        if sub.restartable and sub.restarts < self.restart_budget:
            restarted = self._restart(sub)
        if (not restarted and sub.restartable) or sub.stalls >= _DUMP_AFTER_STALLS:
            # Budget exhausted or chronically stalling: evidence to disk.
            if sub.name not in self._dumped:
                self._dumped.add(sub.name)
                lifecycle.dump_crash(f"watchdog-stall:{sub.name}")

    def _restart(self, sub: _Subsystem) -> bool:
        fn = sub.restart
        try:
            if fn is not None:
                ok = fn() is not False
            elif self.restarter is not None:
                ok = self.restarter(sub.name) is not False
            else:
                return False
        except Exception:
            log.exception("watchdog: restart of %s failed", sub.name)
            return False
        if ok:
            sub.restarts += 1
            watchdog_restarts_total.inc(subsystem=sub.name)
            # Fresh grace window for the restarted thread, and re-arm the
            # stall edge so a restart that does not take is re-detected.
            with self._lock:
                sub.last_beat = self._clock()
                sub.stalled = False
            log.warning(
                "watchdog: restarted %s (restart %d/%d)",
                sub.name, sub.restarts, self.restart_budget,
            )
        return ok

    # ------------------------------------------------------------------
    def run(self, stop_event: threading.Event) -> None:
        """Manager runnable: scan on a fixed cadence; must never die."""
        while not stop_event.wait(self.scan_period):
            try:
                self.scan()
            except Exception:  # pragma: no cover - must never die
                log.exception("watchdog scan failed")

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """The /debug/watchdog payload."""
        now = self._clock()
        with self._lock:
            subs = {
                s.name: {
                    "last_beat_age_s": round(now - s.last_beat, 3),
                    "stall_after_s": s.stall_after,
                    "stalled": s.stalled,
                    "stalls": s.stalls,
                    "restarts": s.restarts,
                    "restartable": s.restartable,
                    "beats": s.beats,
                }
                for s in self._subsystems.values()
            }
        return {
            "scan_period_s": self.scan_period,
            "restart_budget": self.restart_budget,
            "subsystems": subs,
            "last_stall_burst": self._last_burst,
        }


class _WatchdogRef:
    """Recorder shim: event against a subsystem by name without an object."""

    KIND = "Watchdog"

    def __init__(self, name: str) -> None:
        from types import SimpleNamespace

        self.metadata = SimpleNamespace(name=name)
