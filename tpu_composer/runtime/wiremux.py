"""Multiplexed framed wire transport — the v2 store wire plane.

One persistent socket per (client, apiserver) pair carries every verb and
every watch concurrently: length-prefixed JSON frames with correlation ids,
pipelined from all controller threads, with watch events arriving as
server-push frames on the same connection. This is the Dagger/RPCAcc lesson
(PAPERS.md): per-request HTTP overhead — request lines, header parsing, a
server thread handoff per verb, and one dedicated socket per watch —
dominates tight RPC paths; a framed mux amortizes all of it over a single
connection.

Protocol (version ``tpuc-mux/1``):

- Handshake: a plain HTTP/1.1 ``GET /mux`` with ``Upgrade: tpuc-mux/1``;
  the server answers ``101 Switching Protocols`` and both sides switch to
  framed mode on the same socket. A server that answers anything else does
  not speak mux — the client falls back to HTTP permanently (the
  degraded-to-HTTP runbook row in docs/OPERATIONS.md).
- Frames: 4-byte big-endian unsigned length, then that many bytes of UTF-8
  JSON. Hard cap ``MAX_FRAME`` guards against corrupt prefixes.
- Client → server:
    ``{"id": N, "method": "GET|POST|PUT|DELETE", "path": ..., "body": ...}``
      one verb; a path carrying ``watch=true`` opens a watch stream whose
      stream id IS the request id.
    ``{"cancel": N}`` — stop watch stream N.
    ``{"ping": N}`` — liveness probe (client-initiated, answered inline).
- Server → client:
    ``{"id": N, "code": C, "body": {...}}`` — verb response (or the watch
      accept/denial: a watch ack carries ``"watch": true``).
    ``{"watch": N, "event": {...}}`` — one watch event (same JSON the HTTP
      chunked watch writes per line, including the 410 ERROR persona).
    ``{"watch": N, "end": true}`` — stream N ended server-side.
    ``{"pong": N}`` — answer to ping N.

Liveness: after the handshake the socket is fully blocking, so a silent
partition (NAT drop, half-open peer) would otherwise stall every pending
correlation id until its individual request timeout and leave watches
waiting out their idle period. With ``ping_period > 0`` the client probes
the transport with ping frames; a pong outstanding past
``ping_misses x ping_period`` declares the connection dead and fails ALL
pending verbs and watch streams at once — detection within ~2x the ping
period at ``ping_misses=1``, versus the ~30s per-request timeout baseline.
Sends carry their own wall deadline (``send_timeout``) so a peer that
stops draining the socket can never wedge a controller thread inside a
blocking ``sendall``. Reconnects back off (bounded) and fail fast while
the backoff window is open.

Method/path/body are byte-identical to the HTTP path, so everything keyed
on them — the sim apiserver's request_log assertions, fail-hook personas,
``watch_blocker``'s ``"watch=true" in path`` match — behaves the same with
the mux on or off. ``TPUC_WIRE_MUX=0`` / ``--no-wire-mux`` disables the
client entirely and the PR 17 keep-alive HTTP path runs untouched.
"""

from __future__ import annotations

import itertools
import json
import logging
import queue
import select
import socket
import ssl
import struct
import threading
import time
import urllib.parse
from typing import Any, Callable, Dict, Optional, Tuple

from tpu_composer.runtime.metrics import (
    wire_mux_reconnects_total,
    wire_ping_rtt_seconds,
)

log = logging.getLogger("wiremux")

#: Protocol token in the Upgrade header; bump on incompatible frame changes.
PROTOCOL = "tpuc-mux/1"

#: Upgrade endpoint path on the apiserver.
MUX_PATH = "/mux"

#: Refuse frames larger than this — a corrupt length prefix must not make
#: us try to allocate gigabytes.
MAX_FRAME = 64 * 1024 * 1024

#: Per-write chunk on TLS connections, where MSG_DONTWAIT is unavailable
#: (``ssl.SSLSocket.send`` rejects flags): small enough that one blocking
#: SSL_write of a chunk-sized record drains quickly even against a nearly
#: full socket buffer, so the deadline loop in ``_send_bytes`` keeps
#: control between chunks.
TLS_SEND_CHUNK = 4096

_LEN = struct.Struct(">I")


class MuxError(Exception):
    """Transport-level mux failure (connect, send, connection died)."""


class MuxUnsupported(MuxError):
    """The server rejected the /mux upgrade: fall back to HTTP for good."""


class MuxHTTPError(MuxError):
    """An API error response frame (code >= 400); carries the Status body."""

    def __init__(self, code: int, body: Any) -> None:
        super().__init__(f"HTTP {code}")
        self.code = code
        self.body = body if isinstance(body, dict) else {"message": str(body)}


# ----------------------------------------------------------------------
# frame codec (shared by client and the sim apiserver's mux endpoint)
# ----------------------------------------------------------------------
def encode_frame(obj: Dict[str, Any]) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode()
    return _LEN.pack(len(payload)) + payload


def read_exact(fp, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes from a file-like object, riding out partial
    reads across frame boundaries. None on clean EOF at a frame boundary;
    MuxError on EOF mid-frame (truncated peer)."""
    chunks = []
    got = 0
    while got < n:
        chunk = fp.read(n - got)
        if not chunk:
            if got == 0:
                return None
            raise MuxError(f"truncated frame: wanted {n} bytes, got {got}")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(fp) -> Optional[Dict[str, Any]]:
    """One frame off a blocking file-like object; None on clean EOF."""
    head = read_exact(fp, _LEN.size)
    if head is None:
        return None
    (size,) = _LEN.unpack(head)
    if size > MAX_FRAME:
        raise MuxError(f"frame of {size} bytes exceeds cap {MAX_FRAME}")
    body = read_exact(fp, size)
    if body is None:
        raise MuxError("EOF between frame header and body")
    try:
        obj = json.loads(body)
    except ValueError as e:
        raise MuxError(f"corrupt frame payload: {e}") from None
    if not isinstance(obj, dict):
        raise MuxError(f"frame payload is {type(obj).__name__}, not an object")
    return obj


# ----------------------------------------------------------------------
# client
# ----------------------------------------------------------------------
class _Pending:
    """One in-flight verb awaiting its response frame."""

    __slots__ = ("event", "code", "body", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.code: Optional[int] = None
        self.body: Any = None
        self.error: Optional[MuxError] = None


class MuxWatch:
    """One watch stream riding the mux connection.

    Iterates JSON-line byte strings — the exact shape ``urllib``'s chunked
    watch response yields line by line — so ``_WatchThread`` consumes both
    transports through one loop. ``shutdown()`` mirrors the raw-socket
    shutdown the HTTP path uses to unblock a reader from another thread.
    """

    _END = object()

    def __init__(self, conn: "_MuxConn", stream_id: int, timeout: float) -> None:
        self._conn = conn
        self._id = stream_id
        self._timeout = timeout
        self._events: "queue.Queue[Any]" = queue.Queue()
        self._closed = False

    # fed by the connection reader thread
    def _push(self, event: Dict[str, Any]) -> None:
        self._events.put(event)

    def _end(self) -> None:
        self._events.put(self._END)

    def _fail(self, err: MuxError) -> None:
        """Connection death: the consumer must learn NOW, and must be able
        to tell this apart from a clean server-side stream end — a clean
        end means "re-list maybe", a dead connection means "reconnect with
        the resume cursor immediately"."""
        self._events.put(err)

    def __iter__(self) -> "MuxWatch":
        return self

    def __next__(self) -> bytes:
        if self._closed:
            raise StopIteration
        try:
            # The per-event timeout doubles as the liveness check, exactly
            # like the HTTP watch's socket timeout: a quiet stream raises
            # and the watch thread reconnects from its resume cursor.
            item = self._events.get(timeout=self._timeout)
        except queue.Empty:
            raise socket.timeout(f"mux watch {self._id}: idle") from None
        if item is self._END:
            self._closed = True
            raise StopIteration
        if isinstance(item, MuxError):
            self._closed = True
            raise MuxError(f"mux watch {self._id}: connection died: {item}")
        return (json.dumps(item) + "\n").encode()

    def shutdown(self) -> None:
        """Stop the stream from another thread: best-effort cancel to the
        server, then a local end marker so a blocked __next__ returns."""
        self._conn.cancel_watch(self._id)
        self._end()

    close = shutdown


class _MuxConn:
    """One live framed connection: socket, reader thread, pinger thread,
    correlation maps."""

    def __init__(
        self,
        sock: socket.socket,
        ping_period: float = 0.0,
        ping_misses: int = 2,
        send_timeout: float = 10.0,
        on_dead: Optional[Callable[["_MuxConn"], None]] = None,
        on_alive: Optional[Callable[["_MuxConn"], None]] = None,
    ) -> None:
        self.sock = sock
        self.rfile = sock.makefile("rb")
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._pending: Dict[int, _Pending] = {}
        self._watches: Dict[int, MuxWatch] = {}
        self.dead = threading.Event()
        self._send_timeout = max(0.1, send_timeout)
        self._ping_period = max(0.0, ping_period)
        self._ping_misses = max(1, int(ping_misses))
        self._ping_sent: Dict[int, float] = {}  # seq -> monotonic send time
        self._ping_seq = 0
        self._last_ping = time.monotonic()
        #: Monotonic time of the last frame of ANY kind from the peer —
        #: the liveness clock. Any arriving frame proves the wire, so a
        #: busy connection never false-positives on one slow pong.
        self._last_frame = time.monotonic()
        #: True once any frame arrived on this connection — a connection
        #: that dies frameless counts toward the client's fail streak.
        self.got_frame = False
        self._on_dead = on_dead
        self._on_alive = on_alive
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True, name="mux-reader"
        )
        self._reader.start()
        if self._ping_period > 0:
            self._pinger = threading.Thread(
                target=self._ping_loop, daemon=True, name="mux-pinger"
            )
            self._pinger.start()

    # -- sending -------------------------------------------------------
    def send(self, frame: Dict[str, Any]) -> None:
        data = encode_frame(frame)
        try:
            with self._wlock:
                self._send_bytes(data)
        except MuxError as e:
            self._fail(e)
            raise
        except OSError as e:
            err = MuxError(f"mux send: {e}")
            self._fail(err)
            raise err from None

    def _send_bytes(self, data: bytes) -> None:
        """sendall under a wall deadline: wait-for-writable + partial send,
        so a peer that stops draining (full TCP buffer, half-open stall)
        fails the connection after ``send_timeout`` instead of wedging the
        calling controller thread inside a blocking ``sendall`` forever.
        Never uses ``settimeout`` — the reader thread shares this socket
        and a timeout surfacing mid-read would corrupt framing."""
        deadline = time.monotonic() + self._send_timeout
        view = memoryview(data)
        sent = 0
        # ssl.SSLSocket.send() raises ValueError for ANY non-zero flags, so
        # the MSG_DONTWAIT trick below is plain-TCP only. TLS instead
        # writes one small record per select-writable wakeup: a blocking
        # SSL_write of a TLS_SEND_CHUNK record parks at most until the
        # kernel drains that one record (not the whole frame), and the
        # deadline check between chunks still bounds total elapsed time —
        # a slightly softer bound than MSG_DONTWAIT's, accepted because
        # O_NONBLOCK/settimeout can't be flipped on the fd the blocking
        # reader thread shares.
        tls = isinstance(self.sock, ssl.SSLSocket)
        while sent < len(view):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise MuxError(
                    f"mux send: peer stalled for {self._send_timeout}s"
                    " with socket buffer full"
                )
            try:
                _, writable, _ = select.select(
                    [], [self.sock], [], min(remaining, 0.25)
                )
            except (OSError, ValueError):
                raise MuxError("mux send: socket closed") from None
            if not writable:
                continue
            try:
                if tls:
                    sent += self.sock.send(view[sent:sent + TLS_SEND_CHUNK])
                else:
                    # MSG_DONTWAIT: non-blocking for THIS call only,
                    # without flipping O_NONBLOCK on the shared fd. A plain
                    # send() on a blocking socket queues the ENTIRE buffer
                    # before returning — against a stalled peer a large
                    # frame wedges forever no matter what select said
                    # (select only guarantees SOME space, not len(view)
                    # of it).
                    sent += self.sock.send(view[sent:], socket.MSG_DONTWAIT)
            except (BlockingIOError, InterruptedError,
                    ssl.SSLWantWriteError, ssl.SSLWantReadError):
                continue
            except ValueError as e:
                # Safety net: a socket variant that rejects flags (or an
                # operation on a torn-down SSL object) must fail the
                # connection as a classified MuxError — never escape as an
                # unhandled ValueError that would kill the calling
                # controller thread unclassified.
                raise MuxError(f"mux send: {e}") from None

    # -- liveness ------------------------------------------------------
    def _ping_loop(self) -> None:
        """Probe the transport with ping frames every ``ping_period``; the
        connection is declared dead when NO frame of any kind (pong,
        response, watch event) has arrived for ``(misses + 0.5) x period``
        while a probe is outstanding. On a healthy idle wire the frame age
        oscillates between ~0 and one period (each probe's pong resets
        it), so the extra half period is the margin that keeps the
        threshold strictly above the probe cadence. Wakes at
        quarter-period granularity; worst-case detection from stall onset
        is ``(misses + 0.75) x period`` — two periods at the bench's
        ``misses=1``, comfortably under any per-request timeout."""
        period = self._ping_period
        deadline = (self._ping_misses + 0.5) * period
        err: Optional[MuxError] = None
        while not self.dead.wait(period / 4.0):
            now = time.monotonic()
            with self._lock:
                stale_for = now - self._last_frame
                if self._ping_sent and stale_for >= deadline:
                    err = MuxError(
                        f"mux liveness: no frame for {stale_for:.2f}s with"
                        f" {len(self._ping_sent)} ping(s) unanswered"
                        f" (deadline {deadline:g}s ="
                        f" (misses {self._ping_misses} + 0.5) x {period:g}s)"
                    )
                    break
                if now - self._last_ping < period:
                    continue
                self._ping_seq += 1
                seq = self._ping_seq
                self._ping_sent[seq] = now
                self._last_ping = now
            try:
                self.send({"ping": seq})
            except MuxError:
                return  # send() already failed the connection
        if err is not None:
            self._fail(err)

    def cancel_watch(self, stream_id: int) -> None:
        with self._lock:
            self._watches.pop(stream_id, None)
        if not self.dead.is_set():
            try:
                self.send({"cancel": stream_id})
            except MuxError:
                pass

    # -- registration --------------------------------------------------
    def add_pending(self, rid: int) -> _Pending:
        p = _Pending()
        with self._lock:
            if self.dead.is_set():
                raise MuxError("mux connection is down")
            self._pending[rid] = p
        return p

    def drop_pending(self, rid: int) -> None:
        with self._lock:
            self._pending.pop(rid, None)

    def add_watch(self, rid: int, w: MuxWatch) -> None:
        with self._lock:
            if self.dead.is_set():
                raise MuxError("mux connection is down")
            self._watches[rid] = w

    # -- reader --------------------------------------------------------
    def _read_loop(self) -> None:
        err: Optional[MuxError] = None
        try:
            while True:
                frame = read_frame(self.rfile)
                if frame is None:
                    err = MuxError("mux connection closed by server")
                    break
                self._dispatch(frame)
        except (MuxError, OSError, ValueError) as e:
            err = e if isinstance(e, MuxError) else MuxError(f"mux read: {e}")
        self._fail(err or MuxError("mux connection closed"))

    def _dispatch(self, frame: Dict[str, Any]) -> None:
        self._last_frame = time.monotonic()
        if not self.got_frame:
            self.got_frame = True
            if self._on_alive is not None:
                self._on_alive(self)
        if "pong" in frame:
            with self._lock:
                sent_at = self._ping_sent.pop(frame["pong"], None)
            if sent_at is not None:
                wire_ping_rtt_seconds.observe(time.monotonic() - sent_at)
            return
        if "watch" in frame and "id" not in frame:
            sid = frame["watch"]
            with self._lock:
                w = self._watches.get(sid)
                if frame.get("end"):
                    self._watches.pop(sid, None)
            if w is None:
                return
            if frame.get("end"):
                w._end()
            else:
                w._push(frame.get("event") or {})
            return
        rid = frame.get("id")
        with self._lock:
            p = self._pending.pop(rid, None)
        if p is None:
            return  # response to a request whose waiter timed out
        p.code = int(frame.get("code", 500))
        p.body = frame.get("body")
        p.event.set()

    def _fail(self, err: MuxError) -> None:
        """Connection is gone: everything in flight fails AT ONCE — every
        pending verb and every watch stream, not serially via per-request
        timeouts. Watch consumers get a distinguishable connection-death
        error so they reconnect from their resume cursor immediately."""
        with self._lock:
            if self.dead.is_set():
                return
            self.dead.set()
            pending = list(self._pending.values())
            self._pending.clear()
            watches = list(self._watches.values())
            self._watches.clear()
            self._ping_sent.clear()
        for p in pending:
            p.error = err
            p.event.set()
        for w in watches:
            w._fail(err)
        if self._on_dead is not None:
            self._on_dead(self)
        self.close()

    def close(self) -> None:
        self.dead.set()
        try:
            self.sock.close()
        except OSError:
            pass


class MuxClient:
    """Multiplexed apiserver client: one connection, many concurrent verbs
    and watches. Thread-safe; reconnects transparently on the next call
    after a connection loss (watch consumers re-open their own streams)."""

    def __init__(
        self,
        base_url: str,
        ssl_context: Optional[ssl.SSLContext] = None,
        token: Optional[str] = None,
        connect_timeout: float = 5.0,
        ping_period: float = 5.0,
        ping_misses: int = 2,
        send_timeout: float = 10.0,
        redial_backoff_max: float = 2.0,
    ) -> None:
        split = urllib.parse.urlsplit(base_url)
        self._host = split.hostname or "127.0.0.1"
        self._port = split.port or (443 if split.scheme == "https" else 80)
        self._tls = split.scheme == "https"
        self._ssl_ctx = ssl_context
        self._token = token
        self._connect_timeout = connect_timeout
        self._ping_period = max(0.0, ping_period)
        self._ping_misses = max(1, int(ping_misses))
        self._send_timeout = send_timeout
        self._redial_backoff_max = max(0.05, redial_backoff_max)
        self._ids = itertools.count(1)
        self._conn: Optional[_MuxConn] = None
        self._conn_lock = threading.Lock()
        self._closed = False
        self._backoff = 0.0
        self._next_dial = 0.0  # monotonic gate: fail fast while it's open
        self._dialed_once = False
        #: Consecutive connection-level failures (failed handshakes plus
        #: connections that died before serving a single frame) — NEVER
        #: per-request failures. The kubestore's flap damper reads this.
        #: Mutated from the dialing thread (under ``_conn_lock``) AND from
        #: reader/pinger-thread death/alive callbacks, so every mutation
        #: takes ``_streak_lock`` — racing unlocked ``+=``/``= 0`` could
        #: lose an increment or a reset and delay (or falsely trip) the
        #: K-streak mux->HTTP demotion.
        self.fail_streak = 0
        self._streak_lock = threading.Lock()

    # -- connection management -----------------------------------------
    def _handshake(self) -> _MuxConn:
        try:
            sock = socket.create_connection(
                (self._host, self._port), timeout=self._connect_timeout
            )
        except OSError as e:
            raise MuxError(f"mux connect {self._host}:{self._port}: {e}") from None
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        try:
            if self._tls:
                ctx = self._ssl_ctx or ssl.create_default_context()
                sock = ctx.wrap_socket(sock, server_hostname=self._host)
            lines = [
                f"GET {MUX_PATH} HTTP/1.1",
                f"Host: {self._host}:{self._port}",
                f"Upgrade: {PROTOCOL}",
                "Connection: Upgrade",
            ]
            if self._token:
                lines.append(f"Authorization: Bearer {self._token}")
            sock.sendall(("\r\n".join(lines) + "\r\n\r\n").encode())
            # Read the HTTP response head byte-by-byte up to the blank line —
            # anything past it is the first frame and must stay in the stream.
            head = b""
            while b"\r\n\r\n" not in head:
                b1 = sock.recv(1)
                if not b1:
                    raise MuxError("mux handshake: connection closed")
                head += b1
                if len(head) > 65536:
                    raise MuxError("mux handshake: oversized response head")
            status = head.split(b"\r\n", 1)[0].decode(errors="replace")
            parts = status.split()
            if len(parts) < 2 or parts[1] != "101":
                raise MuxUnsupported(
                    f"server declined mux upgrade: {status!r}"
                )
        except MuxError:
            sock.close()
            raise
        except OSError as e:
            sock.close()
            raise MuxError(f"mux handshake: {e}") from None
        # Handshake done: clear the connect timeout — reads are framed and
        # blocking from here; per-request deadlines live client-side and
        # the ping deadline covers transport liveness.
        sock.settimeout(None)
        return _MuxConn(
            sock,
            ping_period=self._ping_period,
            ping_misses=self._ping_misses,
            send_timeout=self._send_timeout,
            on_dead=self._conn_died,
            on_alive=self._conn_alive,
        )

    def _conn_died(self, conn: "_MuxConn") -> None:
        # Reader/pinger-thread callback: a connection that never served a
        # frame is a connection-level failure episode.
        if not conn.got_frame:
            with self._streak_lock:
                self.fail_streak += 1

    def _conn_alive(self, conn: "_MuxConn") -> None:
        with self._streak_lock:
            self.fail_streak = 0

    def _ensure_conn(self) -> _MuxConn:
        conn = self._conn
        if conn is not None and not conn.dead.is_set():
            return conn
        with self._conn_lock:
            if self._closed:
                raise MuxError("mux client closed")
            conn = self._conn
            if conn is not None and not conn.dead.is_set():
                return conn
            now = time.monotonic()
            if now < self._next_dial:
                raise MuxError(
                    f"mux reconnect backoff: retry in"
                    f" {self._next_dial - now:.2f}s after"
                    f" {self.fail_streak} consecutive connection failures"
                )
            try:
                conn = self._handshake()
            except MuxUnsupported:
                raise  # permanent verdict, not a flap: no backoff/streak
            except MuxError:
                with self._streak_lock:
                    self.fail_streak += 1
                self._backoff = min(
                    max(self._backoff * 2.0, 0.05), self._redial_backoff_max
                )
                self._next_dial = time.monotonic() + self._backoff
                raise
            self._backoff = 0.0
            self._next_dial = 0.0
            if self._dialed_once:
                wire_mux_reconnects_total.inc()
                log.info(
                    "mux reconnected to %s:%s (watches resume from cache"
                    " cursor)", self._host, self._port,
                )
            self._dialed_once = True
            self._conn = conn
            return conn

    # -- verbs ---------------------------------------------------------
    def request(
        self,
        method: str,
        path: str,
        body: Optional[Dict[str, Any]] = None,
        timeout: float = 30.0,
        idempotent: bool = False,
    ) -> Tuple[int, Any]:
        """One pipelined verb. Returns (status code, decoded body).

        Retry classification: a failure BEFORE the frame left this process
        ("never sent" — dead pooled connection, registration on a dying
        connection) is safe to retry for ANY verb, the same recovery the
        keep-alive HTTP path does. A connection death WHILE the request is
        in flight is ambiguous — the server may or may not have executed
        the verb — so it is retried once only when the caller declares the
        verb ``idempotent`` (reads, CAS-guarded updates); otherwise it
        surfaces as MuxError so the caller's requeue + nonce machinery
        resolves the ambiguity. A response timeout always raises."""
        for attempt in (0, 1):
            conn = self._ensure_conn()
            rid = next(self._ids)
            try:
                pending = conn.add_pending(rid)
            except MuxError:
                if attempt == 0:
                    continue  # never sent: safe for any verb
                raise
            try:
                conn.send({"id": rid, "method": method, "path": path,
                           "body": body})
            except MuxError:
                conn.drop_pending(rid)
                if attempt == 0:
                    continue  # never sent: safe for any verb
                raise
            if not pending.event.wait(timeout):
                conn.drop_pending(rid)
                raise MuxError(f"{method} {path}: mux response timeout")
            if pending.error is not None:
                # In flight when the connection died: ambiguous.
                if idempotent and attempt == 0:
                    continue
                raise pending.error
            return pending.code or 500, pending.body
        raise MuxError(f"{method} {path}: mux retry fell through")

    def watch(self, path: str, timeout: float = 30.0) -> MuxWatch:
        """Open a watch stream (path carries ``watch=true`` + resume rv).
        Returns once the server acks; raises MuxHTTPError on denial (e.g.
        a fail-hook 503) so callers map it like an HTTP error status."""
        conn = self._ensure_conn()
        rid = next(self._ids)
        pending = conn.add_pending(rid)
        w = MuxWatch(conn, rid, timeout)
        conn.add_watch(rid, w)
        try:
            conn.send({"id": rid, "method": "GET", "path": path, "body": None})
        except MuxError:
            conn.drop_pending(rid)
            raise
        if not pending.event.wait(timeout):
            conn.drop_pending(rid)
            conn.cancel_watch(rid)
            raise MuxError(f"GET {path}: mux watch-open timeout")
        if pending.error is not None:
            raise pending.error
        if (pending.code or 500) >= 400:
            with conn._lock:
                conn._watches.pop(rid, None)
            raise MuxHTTPError(pending.code or 500, pending.body)
        return w

    def close(self) -> None:
        with self._conn_lock:
            self._closed = True
            conn, self._conn = self._conn, None
        if conn is not None:
            conn.close()
