"""Multiplexed framed wire transport — the v2 store wire plane.

One persistent socket per (client, apiserver) pair carries every verb and
every watch concurrently: length-prefixed JSON frames with correlation ids,
pipelined from all controller threads, with watch events arriving as
server-push frames on the same connection. This is the Dagger/RPCAcc lesson
(PAPERS.md): per-request HTTP overhead — request lines, header parsing, a
server thread handoff per verb, and one dedicated socket per watch —
dominates tight RPC paths; a framed mux amortizes all of it over a single
connection.

Protocol (version ``tpuc-mux/1``):

- Handshake: a plain HTTP/1.1 ``GET /mux`` with ``Upgrade: tpuc-mux/1``;
  the server answers ``101 Switching Protocols`` and both sides switch to
  framed mode on the same socket. A server that answers anything else does
  not speak mux — the client falls back to HTTP permanently (the
  degraded-to-HTTP runbook row in docs/OPERATIONS.md).
- Frames: 4-byte big-endian unsigned length, then that many bytes of UTF-8
  JSON. Hard cap ``MAX_FRAME`` guards against corrupt prefixes.
- Client → server:
    ``{"id": N, "method": "GET|POST|PUT|DELETE", "path": ..., "body": ...}``
      one verb; a path carrying ``watch=true`` opens a watch stream whose
      stream id IS the request id.
    ``{"cancel": N}`` — stop watch stream N.
- Server → client:
    ``{"id": N, "code": C, "body": {...}}`` — verb response (or the watch
      accept/denial: a watch ack carries ``"watch": true``).
    ``{"watch": N, "event": {...}}`` — one watch event (same JSON the HTTP
      chunked watch writes per line, including the 410 ERROR persona).
    ``{"watch": N, "end": true}`` — stream N ended server-side.

Method/path/body are byte-identical to the HTTP path, so everything keyed
on them — the sim apiserver's request_log assertions, fail-hook personas,
``watch_blocker``'s ``"watch=true" in path`` match — behaves the same with
the mux on or off. ``TPUC_WIRE_MUX=0`` / ``--no-wire-mux`` disables the
client entirely and the PR 17 keep-alive HTTP path runs untouched.
"""

from __future__ import annotations

import itertools
import json
import logging
import queue
import socket
import ssl
import struct
import threading
import urllib.parse
from typing import Any, Dict, Optional, Tuple

log = logging.getLogger("wiremux")

#: Protocol token in the Upgrade header; bump on incompatible frame changes.
PROTOCOL = "tpuc-mux/1"

#: Upgrade endpoint path on the apiserver.
MUX_PATH = "/mux"

#: Refuse frames larger than this — a corrupt length prefix must not make
#: us try to allocate gigabytes.
MAX_FRAME = 64 * 1024 * 1024

_LEN = struct.Struct(">I")


class MuxError(Exception):
    """Transport-level mux failure (connect, send, connection died)."""


class MuxUnsupported(MuxError):
    """The server rejected the /mux upgrade: fall back to HTTP for good."""


class MuxHTTPError(MuxError):
    """An API error response frame (code >= 400); carries the Status body."""

    def __init__(self, code: int, body: Any) -> None:
        super().__init__(f"HTTP {code}")
        self.code = code
        self.body = body if isinstance(body, dict) else {"message": str(body)}


# ----------------------------------------------------------------------
# frame codec (shared by client and the sim apiserver's mux endpoint)
# ----------------------------------------------------------------------
def encode_frame(obj: Dict[str, Any]) -> bytes:
    payload = json.dumps(obj, separators=(",", ":")).encode()
    return _LEN.pack(len(payload)) + payload


def read_exact(fp, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes from a file-like object, riding out partial
    reads across frame boundaries. None on clean EOF at a frame boundary;
    MuxError on EOF mid-frame (truncated peer)."""
    chunks = []
    got = 0
    while got < n:
        chunk = fp.read(n - got)
        if not chunk:
            if got == 0:
                return None
            raise MuxError(f"truncated frame: wanted {n} bytes, got {got}")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(fp) -> Optional[Dict[str, Any]]:
    """One frame off a blocking file-like object; None on clean EOF."""
    head = read_exact(fp, _LEN.size)
    if head is None:
        return None
    (size,) = _LEN.unpack(head)
    if size > MAX_FRAME:
        raise MuxError(f"frame of {size} bytes exceeds cap {MAX_FRAME}")
    body = read_exact(fp, size)
    if body is None:
        raise MuxError("EOF between frame header and body")
    return json.loads(body)


# ----------------------------------------------------------------------
# client
# ----------------------------------------------------------------------
class _Pending:
    """One in-flight verb awaiting its response frame."""

    __slots__ = ("event", "code", "body", "error")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.code: Optional[int] = None
        self.body: Any = None
        self.error: Optional[MuxError] = None


class MuxWatch:
    """One watch stream riding the mux connection.

    Iterates JSON-line byte strings — the exact shape ``urllib``'s chunked
    watch response yields line by line — so ``_WatchThread`` consumes both
    transports through one loop. ``shutdown()`` mirrors the raw-socket
    shutdown the HTTP path uses to unblock a reader from another thread.
    """

    _END = object()

    def __init__(self, conn: "_MuxConn", stream_id: int, timeout: float) -> None:
        self._conn = conn
        self._id = stream_id
        self._timeout = timeout
        self._events: "queue.Queue[Any]" = queue.Queue()
        self._closed = False

    # fed by the connection reader thread
    def _push(self, event: Dict[str, Any]) -> None:
        self._events.put(event)

    def _end(self) -> None:
        self._events.put(self._END)

    def __iter__(self) -> "MuxWatch":
        return self

    def __next__(self) -> bytes:
        if self._closed:
            raise StopIteration
        try:
            # The per-event timeout doubles as the liveness check, exactly
            # like the HTTP watch's socket timeout: a quiet stream raises
            # and the watch thread reconnects from its resume cursor.
            item = self._events.get(timeout=self._timeout)
        except queue.Empty:
            raise socket.timeout(f"mux watch {self._id}: idle") from None
        if item is self._END:
            self._closed = True
            raise StopIteration
        return (json.dumps(item) + "\n").encode()

    def shutdown(self) -> None:
        """Stop the stream from another thread: best-effort cancel to the
        server, then a local end marker so a blocked __next__ returns."""
        self._conn.cancel_watch(self._id)
        self._end()

    close = shutdown


class _MuxConn:
    """One live framed connection: socket, reader thread, correlation maps."""

    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.rfile = sock.makefile("rb")
        self._wlock = threading.Lock()
        self._lock = threading.Lock()
        self._pending: Dict[int, _Pending] = {}
        self._watches: Dict[int, MuxWatch] = {}
        self.dead = threading.Event()
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True, name="mux-reader"
        )
        self._reader.start()

    # -- sending -------------------------------------------------------
    def send(self, frame: Dict[str, Any]) -> None:
        data = encode_frame(frame)
        try:
            with self._wlock:
                self.sock.sendall(data)
        except OSError as e:
            self._fail(MuxError(f"mux send: {e}"))
            raise MuxError(f"mux send: {e}") from None

    def cancel_watch(self, stream_id: int) -> None:
        with self._lock:
            self._watches.pop(stream_id, None)
        if not self.dead.is_set():
            try:
                self.send({"cancel": stream_id})
            except MuxError:
                pass

    # -- registration --------------------------------------------------
    def add_pending(self, rid: int) -> _Pending:
        p = _Pending()
        with self._lock:
            if self.dead.is_set():
                raise MuxError("mux connection is down")
            self._pending[rid] = p
        return p

    def drop_pending(self, rid: int) -> None:
        with self._lock:
            self._pending.pop(rid, None)

    def add_watch(self, rid: int, w: MuxWatch) -> None:
        with self._lock:
            if self.dead.is_set():
                raise MuxError("mux connection is down")
            self._watches[rid] = w

    # -- reader --------------------------------------------------------
    def _read_loop(self) -> None:
        err: Optional[MuxError] = None
        try:
            while True:
                frame = read_frame(self.rfile)
                if frame is None:
                    err = MuxError("mux connection closed by server")
                    break
                self._dispatch(frame)
        except (MuxError, OSError, ValueError) as e:
            err = e if isinstance(e, MuxError) else MuxError(f"mux read: {e}")
        self._fail(err or MuxError("mux connection closed"))

    def _dispatch(self, frame: Dict[str, Any]) -> None:
        if "watch" in frame and "id" not in frame:
            sid = frame["watch"]
            with self._lock:
                w = self._watches.get(sid)
                if frame.get("end"):
                    self._watches.pop(sid, None)
            if w is None:
                return
            if frame.get("end"):
                w._end()
            else:
                w._push(frame.get("event") or {})
            return
        rid = frame.get("id")
        with self._lock:
            p = self._pending.pop(rid, None)
        if p is None:
            return  # response to a request whose waiter timed out
        p.code = int(frame.get("code", 500))
        p.body = frame.get("body")
        p.event.set()

    def _fail(self, err: MuxError) -> None:
        """Connection is gone: everything in flight fails, every watch
        stream ends (its consumer reconnects with a resume cursor)."""
        with self._lock:
            if self.dead.is_set():
                return
            self.dead.set()
            pending = list(self._pending.values())
            self._pending.clear()
            watches = list(self._watches.values())
            self._watches.clear()
        for p in pending:
            p.error = err
            p.event.set()
        for w in watches:
            w._end()
        self.close()

    def close(self) -> None:
        self.dead.set()
        try:
            self.sock.close()
        except OSError:
            pass


class MuxClient:
    """Multiplexed apiserver client: one connection, many concurrent verbs
    and watches. Thread-safe; reconnects transparently on the next call
    after a connection loss (watch consumers re-open their own streams)."""

    def __init__(
        self,
        base_url: str,
        ssl_context: Optional[ssl.SSLContext] = None,
        token: Optional[str] = None,
        connect_timeout: float = 10.0,
    ) -> None:
        split = urllib.parse.urlsplit(base_url)
        self._host = split.hostname or "127.0.0.1"
        self._port = split.port or (443 if split.scheme == "https" else 80)
        self._tls = split.scheme == "https"
        self._ssl_ctx = ssl_context
        self._token = token
        self._connect_timeout = connect_timeout
        self._ids = itertools.count(1)
        self._conn: Optional[_MuxConn] = None
        self._conn_lock = threading.Lock()
        self._closed = False

    # -- connection management -----------------------------------------
    def _handshake(self) -> _MuxConn:
        try:
            sock = socket.create_connection(
                (self._host, self._port), timeout=self._connect_timeout
            )
        except OSError as e:
            raise MuxError(f"mux connect {self._host}:{self._port}: {e}") from None
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        try:
            if self._tls:
                ctx = self._ssl_ctx or ssl.create_default_context()
                sock = ctx.wrap_socket(sock, server_hostname=self._host)
            lines = [
                f"GET {MUX_PATH} HTTP/1.1",
                f"Host: {self._host}:{self._port}",
                f"Upgrade: {PROTOCOL}",
                "Connection: Upgrade",
            ]
            if self._token:
                lines.append(f"Authorization: Bearer {self._token}")
            sock.sendall(("\r\n".join(lines) + "\r\n\r\n").encode())
            # Read the HTTP response head byte-by-byte up to the blank line —
            # anything past it is the first frame and must stay in the stream.
            head = b""
            while b"\r\n\r\n" not in head:
                b1 = sock.recv(1)
                if not b1:
                    raise MuxError("mux handshake: connection closed")
                head += b1
                if len(head) > 65536:
                    raise MuxError("mux handshake: oversized response head")
            status = head.split(b"\r\n", 1)[0].decode(errors="replace")
            parts = status.split()
            if len(parts) < 2 or parts[1] != "101":
                raise MuxUnsupported(
                    f"server declined mux upgrade: {status!r}"
                )
        except MuxError:
            sock.close()
            raise
        except OSError as e:
            sock.close()
            raise MuxError(f"mux handshake: {e}") from None
        # Handshake done: clear the connect timeout — reads are framed and
        # blocking from here; per-request deadlines live client-side.
        sock.settimeout(None)
        return _MuxConn(sock)

    def _ensure_conn(self) -> _MuxConn:
        conn = self._conn
        if conn is not None and not conn.dead.is_set():
            return conn
        with self._conn_lock:
            if self._closed:
                raise MuxError("mux client closed")
            conn = self._conn
            if conn is not None and not conn.dead.is_set():
                return conn
            conn = self._handshake()
            self._conn = conn
            return conn

    # -- verbs ---------------------------------------------------------
    def request(
        self,
        method: str,
        path: str,
        body: Optional[Dict[str, Any]] = None,
        timeout: float = 30.0,
    ) -> Tuple[int, Any]:
        """One pipelined verb. Returns (status code, decoded body). Retries
        once on a send that hit an already-dead pooled connection (same
        recovery the keep-alive HTTP path does); a connection that dies
        while the request is in flight surfaces as MuxError — the caller's
        normal retry/absorb policy applies."""
        for attempt in (0, 1):
            conn = self._ensure_conn()
            rid = next(self._ids)
            pending = conn.add_pending(rid)
            try:
                conn.send({"id": rid, "method": method, "path": path,
                           "body": body})
            except MuxError:
                conn.drop_pending(rid)
                if attempt == 0:
                    continue
                raise
            if not pending.event.wait(timeout):
                conn.drop_pending(rid)
                raise MuxError(f"{method} {path}: mux response timeout")
            if pending.error is not None:
                raise pending.error
            return pending.code or 500, pending.body
        raise MuxError(f"{method} {path}: mux retry fell through")

    def watch(self, path: str, timeout: float = 30.0) -> MuxWatch:
        """Open a watch stream (path carries ``watch=true`` + resume rv).
        Returns once the server acks; raises MuxHTTPError on denial (e.g.
        a fail-hook 503) so callers map it like an HTTP error status."""
        conn = self._ensure_conn()
        rid = next(self._ids)
        pending = conn.add_pending(rid)
        w = MuxWatch(conn, rid, timeout)
        conn.add_watch(rid, w)
        try:
            conn.send({"id": rid, "method": "GET", "path": path, "body": None})
        except MuxError:
            conn.drop_pending(rid)
            raise
        if not pending.event.wait(timeout):
            conn.drop_pending(rid)
            conn.cancel_watch(rid)
            raise MuxError(f"GET {path}: mux watch-open timeout")
        if pending.error is not None:
            raise pending.error
        if (pending.code or 500) >= 400:
            with conn._lock:
                conn._watches.pop(rid, None)
            raise MuxHTTPError(pending.code or 500, pending.body)
        return w

    def close(self) -> None:
        with self._conn_lock:
            self._closed = True
            conn, self._conn = self._conn, None
        if conn is not None:
            conn.close()
