"""Cluster scheduler: priority, gang admission, preemption, defrag.

The placement subsystem the request controller delegates to instead of
picking nodes inline — see scheduler/core.py for the facade and
docs/ARCHITECTURE.md (Scheduler section) for the data flow.
"""

from tpu_composer.scheduler.core import ClusterScheduler, Placement
from tpu_composer.scheduler.defrag import (
    DefragLoop,
    DefragPlan,
    DefragPlanner,
    Migration,
)
from tpu_composer.scheduler.ledger import DecisionLedger, DecisionRecord
from tpu_composer.scheduler.placement import (
    AllocationError,
    PlacementEngine,
    host_index,
)
from tpu_composer.scheduler.preemption import Preemptor
from tpu_composer.scheduler.queue import PendingEntry, SchedulerQueue

__all__ = [
    "AllocationError",
    "ClusterScheduler",
    "DecisionLedger",
    "DecisionRecord",
    "DefragLoop",
    "DefragPlan",
    "DefragPlanner",
    "Migration",
    "PendingEntry",
    "Placement",
    "PlacementEngine",
    "Preemptor",
    "SchedulerQueue",
    "host_index",
]
