"""ClusterScheduler — the placement authority the request controller asks.

One facade over the four scheduler pieces:

- :class:`~tpu_composer.scheduler.placement.PlacementEngine` scores and
  picks host sets (fragmentation-aware bin-packing + ICI contiguity);
- :class:`~tpu_composer.scheduler.queue.SchedulerQueue` remembers who is
  waiting, at what priority, for what gang demand;
- :class:`~tpu_composer.scheduler.preemption.Preemptor` computes minimal
  victim sets when a high-priority demand cannot fit;
- :class:`~tpu_composer.scheduler.defrag.DefragPlanner` (driven separately
  by the DefragLoop runnable) proposes migrations that reassemble
  contiguous capacity.

``place()`` is the one entry point for fresh slice placements and returns a
:class:`Placement` that either names the hosts (success), names the victims
the caller must evict first (preemption), or raises
:class:`~tpu_composer.scheduler.placement.AllocationError` (queue and
retry). The caller is expected to serialize calls (the request controller's
allocation lock) — the queue itself is thread-safe, but two concurrent
placements would double-book capacity exactly as the inline allocator
would have.

Every decision additionally explains itself through the
:class:`~tpu_composer.scheduler.ledger.DecisionLedger` (when constructed —
``decisions=False`` / TPUC_DECISIONS=0 skips all of it): a placement
records the candidates it considered with per-node verdicts and the
tiebreak that picked the winners; a hold-back records the binding
constraint (which resource, how short); a preemption records the victim
set with its minimality rationale. ``/debug/scheduler/explain/<name>``
serves the ring.
"""

from __future__ import annotations

import contextlib
import threading
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set

from tpu_composer.api.types import ComposabilityRequest
from tpu_composer.runtime import tracing
from tpu_composer.runtime.metrics import (
    scheduler_fragmentation_score,
    scheduler_held_back_total,
    scheduler_queue_depth,
    scheduler_time_to_placement_seconds,
)
from tpu_composer.scheduler import ledger as ledger_mod
from tpu_composer.scheduler import native as sched_native
from tpu_composer.scheduler.defrag import DefragPlanner
from tpu_composer.scheduler.ledger import DecisionLedger, DecisionRecord
from tpu_composer.scheduler.placement import AllocationError, PlacementEngine
from tpu_composer.scheduler.preemption import Preemptor
from tpu_composer.scheduler.snapshot import ChipIndexSnapshot
from tpu_composer.scheduler.queue import PendingEntry, SchedulerQueue
from tpu_composer.topology.slices import SliceShape

#: Inputs-digest bounds: a 10k-node cluster must not serialize 10k-entry
#: maps into every record — past the cap the digest keeps the distribution
#: (free-ports -> host count) instead of the per-node map.
_DIGEST_NODE_CAP = 64
# The ledger owns the candidates-per-record truncation policy; the
# scheduler threads it into the engine's verdict scan so no more than
# this many candidate dicts are ever materialized per decision.
_CANDIDATE_CAP = ledger_mod.CANDIDATE_CAP


@dataclass
class Placement:
    """Outcome of a placement decision: hosts to use, or victims to evict
    first (mutually exclusive — victims non-empty means no hosts yet)."""

    nodes: List[str] = field(default_factory=list)
    victims: List[str] = field(default_factory=list)


def _rejection_class(verdict: str) -> str:
    """Collapse a per-node verdict into the binding-resource vocabulary
    the held-back metric labels with."""
    if verdict.startswith("no-tpu-ports"):
        return "tpu-ports"
    if verdict == "node-resources":
        return "node-resources"
    if verdict == "quarantined":
        return "quarantined"
    if verdict in ("not-ready", "cordoned"):
        return "node-unavailable"
    return verdict


class ClusterScheduler:
    def __init__(
        self,
        store,
        defrag_mode: str = "delete",
        decisions: bool = True,
        recorder=None,  # duck-typed EventRecorder for ledger events
        native_sched: Optional[bool] = None,  # None = TPUC_NATIVE_SCHED
    ) -> None:
        self.store = store
        # Snapshot + native-kernel layer (--native-sched, default on):
        # incrementally-maintained packed arrays replace the per-decision
        # store walks, and the fit/verdict/victim scans run in
        # native/tpusched.cc when built. The snapshot declines stores it
        # cannot watch losslessly (e.g. chaos wrappers) and the kernel
        # declines to load when the .so is absent — each falls back one
        # layer with bit-identical decisions.
        if native_sched is None:
            native_sched = sched_native.native_sched_enabled()
        self.snapshot: Optional[ChipIndexSnapshot] = None
        native = None
        if native_sched:
            try:
                snap = ChipIndexSnapshot(store)
            except Exception:
                snap = None
            if snap is not None and snap.active:
                self.snapshot = snap
                native = sched_native.native_lib()
        self.engine = PlacementEngine(
            store, snapshot=self.snapshot, native=native
        )
        self.queue = SchedulerQueue()
        self.preemptor = Preemptor(store, self.engine)
        # THE allocation lock: the request controller serializes its
        # placement passes on it, and the defrag executor takes it around
        # each verify+delete — without the shared lock, defrag's capacity
        # re-verification could be invalidated by a concurrent placement
        # between its check and its delete, evicting a Running worker
        # with nowhere to re-land.
        self.alloc_lock = threading.Lock()
        # Decision ledger (scheduler/ledger.py): every decision records
        # its inputs, candidates, choice rationale and binding constraint.
        # decisions=False (cmd/main --no-decisions / TPUC_DECISIONS=0)
        # constructs NONE of it — no records, no verdict scans, no events.
        self.ledger: Optional[DecisionLedger] = (
            DecisionLedger(recorder=recorder) if decisions else None
        )
        # defrag_mode="migrate" (cmd/main's default with live migration
        # enabled) makes the executor emit evacuation marks the owners'
        # migration drivers act on make-before-break; "delete" keeps the
        # legacy delete/re-solve executor (escape hatch + direct tests).
        self.defrag = DefragPlanner(
            store, self.engine, queue=self.queue, lock=self.alloc_lock,
            mode=defrag_mode, decision_ledger=self.ledger,
        )

    # ------------------------------------------------------------------
    def place(
        self,
        req: ComposabilityRequest,
        shape: SliceShape,
        quarantined: Set[str],
    ) -> Placement:
        """Arbitrated placement for a fresh slice allocation."""
        # One store pass, two views: `occupied` (every live claim — what
        # the gate and the fragmentation gauge must see) and `used` (minus
        # this request's own children — what its own picking must see).
        occupied, used = self.engine.capacity_maps(req.name)
        self.queue.prune(self.store)
        demand = {"num_hosts": shape.num_hosts,
                  "chips_per_host": shape.chips_per_host}
        with self._decision_span(req) as ctx:
            try:
                nodes = self.engine.pick_hosts(
                    req, shape, quarantined, used=used
                )
            except AllocationError:
                self.queue.note_pending(
                    req, shape.num_hosts, shape.chips_per_host
                )
                self._update_gauges(quarantined, occupied)
                victims = self.preemptor.compute_victims(
                    req, shape, quarantined, used
                )
                if victims:
                    self._record_preempt(
                        req, demand, victims, quarantined, occupied, used,
                        ctx=ctx,
                    )
                    return Placement(victims=victims)
                self._hold_back(
                    req, demand, quarantined, occupied, used,
                    chips=shape.chips_per_host, ctx=ctx,
                )
                raise
            self._admit(
                req, {n: shape.chips_per_host for n in nodes}, occupied,
                quarantined,
                pending_demand=(shape.num_hosts, shape.chips_per_host),
                ctx=ctx,
            )
            self._record_placed(
                req, ledger_mod.KIND_PLACE, demand, nodes, quarantined,
                occupied, used, chips=shape.chips_per_host, ctx=ctx,
            )
            self._assume(req.name, nodes, shape.chips_per_host)
        return Placement(nodes=nodes)

    def place_scalar(
        self,
        req: ComposabilityRequest,
        count: int,
        existing,
        quarantined: Set[str],
    ) -> List[str]:
        """Arbitrated scalar (gpu/cxlmemory) placement: scalar devices
        consume the same per-host ports as slice workers, so they go
        through the same pending queue and backfill gate — a priority-0
        gpu request must not grab the last free port a feasible
        higher-priority slice is queued for. No preemption, though:
        evicting a gang for an independent device is never worth the
        disruption, and scalar requests themselves recover by waiting."""
        occupied, used = self.engine.capacity_maps(req.name)
        self.queue.prune(self.store)
        # Demand bookkeeping for the gate's feasibility probes: pinned /
        # samenode requests need ONE host with room for the DELTA
        # (anchored — growth can't move elsewhere); spread policies need
        # `count` hosts with one port each. The demand must be the delta,
        # not delta+held: probes run against the full `occupied` map,
        # which already counts the devices the request holds — adding
        # them again would double-count and make the gate call a
        # satisfiable anchored request 'unsatisfiable', dropping its
        # protection exactly when it needs it.
        res = req.spec.resource
        existing = list(existing)
        exclude: tuple = ()
        if res.target_node:
            anchor = res.target_node
            demand = (1, count)
        elif res.allocation_policy == "samenode":
            # One host must take the whole delta; a not-yet-anchored
            # request can still land anywhere (anchor "").
            anchor = existing[0] if existing else ""
            demand = (1, count)
        else:
            anchor = ""
            demand = (count, 1)
            if res.allocation_policy == "differentnode":
                # Growth can only land on UNUSED nodes; a probe counting
                # the request's own hosts would overreport feasibility.
                exclude = tuple(sorted(set(existing)))
        demand_doc = {"num_hosts": demand[0], "chips_per_host": demand[1]}
        # Verdict probes must mirror the picker: an anchored demand needs
        # the anchor to fit EVERYTHING the request puts there (already-held
        # devices + the delta) against the request-excluded map — probing
        # the delta alone would call the anchor 'ok' while the picker
        # rejected it (placement.py pick_scalar_nodes already+count check).
        probe_chips = demand[1]
        if anchor:
            probe_chips += sum(1 for e in existing if e == anchor)
        with self._decision_span(req) as ctx:
            try:
                nodes = self.engine.pick_scalar_nodes(
                    req, count, existing, quarantined, used=used
                )
            except AllocationError:
                self.queue.note_pending(req, *demand, anchor=anchor,
                                        exclude_nodes=exclude)
                self._update_gauges(quarantined, occupied)
                self._hold_back(
                    req, demand_doc, quarantined, occupied, used,
                    chips=probe_chips, exclude=set(exclude),
                    kind=ledger_mod.KIND_PLACE_SCALAR, anchor=anchor,
                    ctx=ctx,
                )
                raise
            add: dict = {}
            for n in nodes:
                add[n] = add.get(n, 0) + 1
            self._admit(req, add, occupied, quarantined,
                        pending_demand=demand, anchor=anchor,
                        exclude_nodes=exclude, ctx=ctx,
                        kind=ledger_mod.KIND_PLACE_SCALAR)
            self._record_placed(
                req, ledger_mod.KIND_PLACE_SCALAR, demand_doc, nodes,
                quarantined, occupied, used,
                chips=probe_chips, ctx=ctx,
                exclude=set(exclude),
            )
            if self.snapshot is not None:
                self.snapshot.assume(req.name, add)
        return nodes

    def _admit(
        self,
        req: ComposabilityRequest,
        add,
        occupied,
        quarantined: Set[str],
        pending_demand,
        anchor: str = "",
        exclude_nodes: tuple = (),
        ctx=None,
        kind: str = ledger_mod.KIND_PLACE,
    ) -> None:
        """Run the backfill gate over a tentative placement (`add`: node ->
        ports it would consume) against the FULL occupancy map — including
        the placer's own holdings, or a grow onto a contended host reads
        as free and slips the gate. On pass, dequeue + record wait
        metrics; on hold raise AllocationError naming the protected
        entry."""
        held = self._gate(req, add, occupied, quarantined)
        if held is not None:
            self.queue.note_pending(req, *pending_demand, anchor=anchor,
                                    exclude_nodes=exclude_nodes)
            scheduler_held_back_total.inc(reason="backfill-gate")
            self._update_gauges(quarantined, occupied)
            self._record_gate_hold(req, pending_demand, held, quarantined,
                                   occupied, ctx=ctx, kind=kind)
            raise AllocationError(
                f"held back: pending request {held.name} (priority"
                f" {held.priority} > {req.spec.priority}) needs this"
                " capacity"
            )
        wait = self.queue.note_placed(req.name)
        if wait is not None:
            scheduler_time_to_placement_seconds.observe(
                wait, type=req.spec.resource.type
            )
        self._update_gauges(quarantined, occupied)

    def place_extra(
        self,
        req: ComposabilityRequest,
        shape: SliceShape,
        exclude: Set[str],
        count: int,
        quarantined: Set[str],
    ) -> List[str]:
        """Grow-path placement for the delta workers of a live slice — and
        the replacement-target channel repair and live migration ride. Not
        gated: the slice already holds its capacity and a live resize must
        not deadlock behind the queue — arbitration happened at admission."""
        demand = {"num_hosts": count, "chips_per_host": shape.chips_per_host}
        with self._decision_span(req) as ctx:
            try:
                nodes = self.engine.pick_slice_hosts(
                    req, shape, exclude=exclude, count=count,
                    quarantined=quarantined,
                )
            except AllocationError:
                if self.ledger is not None:
                    occupied, used = self.engine.capacity_maps(req.name)
                    self._hold_back(
                        req, demand, quarantined, occupied, used,
                        chips=shape.chips_per_host, exclude=exclude,
                        kind=ledger_mod.KIND_PLACE_EXTRA, ctx=ctx,
                    )
                else:
                    scheduler_held_back_total.inc(reason="capacity")
                raise
            if self.ledger is not None:
                occupied, used = self.engine.capacity_maps(req.name)
                self._record_placed(
                    req, ledger_mod.KIND_PLACE_EXTRA, demand, nodes,
                    quarantined, occupied, used,
                    chips=shape.chips_per_host, ctx=ctx, exclude=exclude,
                )
            self._assume(req.name, nodes, shape.chips_per_host)
        return nodes

    def _assume(self, request: str, nodes, chips_per_host: int) -> None:
        """Fold a just-granted placement into the snapshot (no-op without
        one): on an async watch store the placeholder rows the controller
        is about to write are not visible yet, and the next decision under
        the lock must not double-book the granted capacity. Superseded by
        the request's real rows when the watch delivers them."""
        if self.snapshot is None:
            return
        claims: Dict[str, int] = {}
        for n in nodes:
            claims[n] = claims.get(n, 0) + chips_per_host
        self.snapshot.assume(request, claims)

    def forget(self, name: str) -> None:
        """Drop a request from the pending queue (deletion path)."""
        self.queue.forget(name)
        scheduler_queue_depth.set(float(self.queue.depth()))

    def requeue(self, req: ComposabilityRequest, num_hosts: int,
                chips_per_host: int) -> None:
        """Re-register a request whose placement was granted but whose
        execution (fabric reservation) failed — the gate protection must
        come back before the backoff retry, and the depth gauge with it.
        (The time-to-placement sample observed at grant time stands; the
        residual wait is re-measured from here.)"""
        self.queue.note_pending(req, num_hosts, chips_per_host)
        scheduler_queue_depth.set(float(self.queue.depth()))
        if self.ledger is not None:
            self.ledger.record(DecisionRecord(
                request=req.name,
                kind=ledger_mod.KIND_PLACE,
                outcome=ledger_mod.OUTCOME_HELD_BACK,
                priority=req.spec.priority,
                demand={"num_hosts": num_hosts,
                        "chips_per_host": chips_per_host},
                binding={"resource": "fabric-reservation"},
                summary=(
                    "placement granted but the fabric reservation failed;"
                    " re-queued with gate protection until the retry"
                ),
            ))

    # ------------------------------------------------------------------
    # decision-ledger recording (every helper below no-ops cheaply when
    # the ledger is off — the TPUC_DECISIONS=0 path builds nothing)
    # ------------------------------------------------------------------
    @contextlib.contextmanager
    def _decision_span(self, req: ComposabilityRequest):
        """A ``scheduler.decide`` span (cat=scheduler) around one decision
        when the ledger is on: the decision id doubles as a trace id, and
        flow handoffs minted inside the span give Perfetto the decision →
        attach arrows. Yields the TraceContext (None when off)."""
        if self.ledger is None or not tracing.enabled():
            yield None
            return
        ctx = tracing.new_trace(f"d-{uuid.uuid4().hex[:10]}")
        with tracing.span(
            "scheduler.decide", cat="scheduler", ctx=ctx, object=req.name,
            decision_id=ctx.trace_id,
        ):
            yield ctx

    def _inputs_digest(
        self, quarantined: Set[str], occupied: Dict[str, int]
    ) -> Dict[str, object]:
        """What the decision saw: free ports per schedulable node (or the
        distribution past the node cap), fragmentation, quarantine set,
        pending-queue depth."""
        free_by_node: Dict[str, int] = {}
        for n in self.engine.schedulable_nodes(quarantined):
            free_by_node[n.metadata.name] = max(
                0, n.status.tpu_slots - occupied.get(n.metadata.name, 0)
            )
        digest: Dict[str, object] = {
            "engine": self.engine.kernel_kind,
            "schedulable_hosts": len(free_by_node),
            "free_chips": sum(free_by_node.values()),
            "fragmentation": round(
                self.engine.fragmentation(quarantined, occupied), 4
            ),
            "queue_depth": self.queue.depth(),
            "quarantined": sorted(quarantined)[:32],
        }
        if len(free_by_node) <= _DIGEST_NODE_CAP:
            digest["free_by_node"] = dict(sorted(free_by_node.items()))
        else:
            dist: Dict[str, int] = {}
            for free in free_by_node.values():
                dist[str(free)] = dist.get(str(free), 0) + 1
            digest["free_distribution"] = dist
        return digest

    def _record_placed(
        self, req, kind, demand, nodes, quarantined, occupied, used,
        chips, ctx, exclude: Set[str] = frozenset(),
    ) -> None:
        if self.ledger is None:
            return
        candidates = self.engine.candidate_verdicts(
            req, chips, quarantined, used, exclude=exclude,
            cap=_CANDIDATE_CAP,
        )
        tiebreak = self.engine.tiebreak_rationale(nodes, used)
        rec = DecisionRecord(
            request=req.name,
            kind=kind,
            outcome=ledger_mod.OUTCOME_PLACED,
            priority=req.spec.priority,
            demand=demand,
            inputs=self._inputs_digest(quarantined, occupied),
            candidates=candidates,
            chosen=list(nodes),
            tiebreak=tiebreak,
            summary=(
                f"placed on {', '.join(nodes)}"
                f" ({demand['num_hosts']}x{demand['chips_per_host']} chips;"
                f" {tiebreak})"
            ),
        )
        if ctx is not None:
            rec.decision_id = ctx.trace_id
            # One flow per planned worker: the resource controller's
            # intent mint consumes them (ledger.link_decision), drawing
            # decision → attach arrows that then ride the nonce trace to
            # Ready.
            rec.flows = [ctx.handoff() for _ in range(len(nodes))]
        self.ledger.record(rec)

    def _hold_back(
        self, req, demand, quarantined, occupied, used, chips,
        exclude: Set[str] = frozenset(),
        kind: str = ledger_mod.KIND_PLACE, anchor: str = "", ctx=None,
    ) -> None:
        """Record a no-capacity hold-back with its binding constraint and
        count it by reason. With the ledger off, only the coarse counter
        moves (no verdict scan); a repeat within the ledger's rescan
        window collapses into the latest record WITHOUT rebuilding the
        candidate verdicts — a queued request's backoff retries must not
        pay O(nodes) scans under the allocation lock per tick."""
        if self.ledger is None:
            scheduler_held_back_total.inc(reason="capacity")
            return
        bumped = self.ledger.bump_if_recent(
            req.name, kind, ledger_mod.OUTCOME_HELD_BACK,
            exclude_resources=("backfill-gate", "fabric-reservation"),
        )
        if bumped is not None:
            scheduler_held_back_total.inc(
                reason=(bumped.binding or {}).get("resource", "capacity")
            )
            return
        candidates = self.engine.candidate_verdicts(
            req, chips, quarantined, used, exclude=exclude
        )
        binding = self._binding_constraint(
            req, demand, candidates, anchor=anchor
        )
        scheduler_held_back_total.inc(reason=binding["resource"])
        fitting = binding.get("fitting_hosts", 0)
        short = binding.get("short_hosts", "")
        self.ledger.record(DecisionRecord(
            request=req.name,
            kind=kind,
            outcome=ledger_mod.OUTCOME_HELD_BACK,
            decision_id=ctx.trace_id if ctx is not None else "",
            priority=req.spec.priority,
            demand=demand,
            inputs=self._inputs_digest(quarantined, occupied),
            candidates=candidates[:_CANDIDATE_CAP],
            binding=binding,
            summary=(
                f"held back: need {demand['num_hosts']} host(s) with"
                f" {demand['chips_per_host']} free TPU port(s), only"
                f" {fitting} fitting — binding: {binding['resource']}"
                + (f", {short} host(s) short" if short else "")
            ),
        ))

    def _binding_constraint(
        self, req, demand, candidates, anchor: str = ""
    ) -> Dict[str, object]:
        """The hold-back's binding constraint: which resource is short and
        by how much, from the candidate verdicts. A pinned demand binds on
        its target node's own verdict; otherwise the dominant rejection
        class among non-fitting nodes names the resource."""
        pinned = anchor or req.spec.resource.target_node
        fitting = sum(1 for c in candidates if c["verdict"] == "ok")
        short = max(0, demand["num_hosts"] - fitting)
        if pinned:
            verdict = next(
                (c["verdict"] for c in candidates if c["node"] == pinned),
                "missing",
            )
            return {
                "resource": "target-node",
                "node": pinned,
                "verdict": verdict,
                "fitting_hosts": fitting,
                "short_hosts": short,
            }
        rejections: Dict[str, int] = {}
        for c in candidates:
            if c["verdict"] == "ok":
                continue
            cls = _rejection_class(str(c["verdict"]))
            rejections[cls] = rejections.get(cls, 0) + 1
        dominant = (
            max(rejections.items(), key=lambda kv: (kv[1], kv[0]))[0]
            if rejections else "tpu-ports"
        )
        return {
            "resource": dominant,
            "needed_hosts": demand["num_hosts"],
            "chips_per_host": demand["chips_per_host"],
            "fitting_hosts": fitting,
            "short_hosts": short,
            "rejections": rejections,
        }

    def _record_gate_hold(
        self, req, pending_demand, held: PendingEntry, quarantined,
        occupied, ctx=None, kind: str = ledger_mod.KIND_PLACE,
    ) -> None:
        if self.ledger is None:
            return
        if self.ledger.bump_if_recent(
            req.name, kind, ledger_mod.OUTCOME_HELD_BACK,
            resource="backfill-gate",
        ) is not None:
            return  # repeat gate hold within the rescan window
        self.ledger.record(DecisionRecord(
            request=req.name,
            kind=kind,
            outcome=ledger_mod.OUTCOME_HELD_BACK,
            decision_id=ctx.trace_id if ctx is not None else "",
            priority=req.spec.priority,
            demand={"num_hosts": pending_demand[0],
                    "chips_per_host": pending_demand[1]},
            inputs=self._inputs_digest(quarantined, occupied),
            binding={
                "resource": "backfill-gate",
                "protecting": held.name,
                "protected_priority": held.priority,
                "protected_demand": {
                    "num_hosts": held.num_hosts,
                    "chips_per_host": held.chips_per_host,
                },
            },
            summary=(
                f"held back by backfill gate: placing now would starve"
                f" pending request {held.name} (priority {held.priority} >"
                f" {req.spec.priority})"
            ),
        ))

    def _record_preempt(
        self, req, demand, victims: List[str], quarantined, occupied, used,
        ctx=None,
    ) -> None:
        if self.ledger is None:
            return
        search = dict(self.preemptor.last_search)
        mode = search.get("mode", "unknown")
        pool = search.get("candidates", "?")
        rationale = (
            f"minimal victim set by {mode} search over {pool} candidate(s)"
            " (cardinality, then total victim priority, then chips evicted)"
        )
        self.ledger.record(DecisionRecord(
            request=req.name,
            kind=ledger_mod.KIND_PLACE,
            outcome=ledger_mod.OUTCOME_PREEMPTING,
            decision_id=ctx.trace_id if ctx is not None else "",
            priority=req.spec.priority,
            demand=demand,
            inputs=self._inputs_digest(quarantined, occupied),
            candidates=self.engine.candidate_verdicts(
                req, demand["chips_per_host"], quarantined, used,
                cap=_CANDIDATE_CAP,
            ),
            victims=list(victims),
            victim_rationale=rationale,
            binding=search,
            summary=(
                f"preempting {', '.join(victims)}"
                f" ({len(victims)} victim(s); {rationale})"
            ),
        ))

    # ------------------------------------------------------------------
    def _gate(
        self,
        req: ComposabilityRequest,
        add,
        occupied,
        quarantined: Set[str],
    ) -> Optional[PendingEntry]:
        """Conservative backfill: block this placement only if it would
        turn a currently-placeable higher-priority pending request into an
        unplaceable one. Probes run against the FULL occupancy map plus
        the tentative placement. Returns the entry being protected, or
        None."""
        entries = self.queue.entries_above(req.spec.priority)
        if not entries:
            return None
        after = dict(occupied)
        for n, chips in add.items():
            after[n] = after.get(n, 0) + chips
        # One node snapshot for all probes (2 per entry) this gate runs.
        nodes = self.engine.schedulable_nodes(quarantined)
        for entry in entries:
            if entry.name == req.name:
                continue
            other = self.store.try_get(ComposabilityRequest, entry.name)
            if other is None or other.being_deleted:
                continue
            feasible_now = self.engine.demand_feasible(
                other, entry.num_hosts, entry.chips_per_host, quarantined,
                occupied, anchor=entry.anchor, nodes=nodes,
                exclude_nodes=entry.exclude_nodes,
            )
            if not feasible_now:
                # Unsatisfiable either way (e.g. its only hosts are
                # quarantined) — holding everyone behind it would be
                # priority inversion for nothing.
                continue
            if not self.engine.demand_feasible(
                other, entry.num_hosts, entry.chips_per_host, quarantined,
                after, anchor=entry.anchor, nodes=nodes,
                exclude_nodes=entry.exclude_nodes,
            ):
                return entry
        return None

    def _update_gauges(self, quarantined: Set[str], occupied) -> None:
        # The gauge must reflect the REAL cluster: `occupied` is the full
        # occupancy map from the pass's single store scan (the
        # request-excluded picking view would read a resizing request's
        # attached chips as free and make the score flap).
        scheduler_queue_depth.set(float(self.queue.depth()))
        scheduler_fragmentation_score.set(
            self.engine.fragmentation(quarantined, occupied)
        )
