"""ClusterScheduler — the placement authority the request controller asks.

One facade over the four scheduler pieces:

- :class:`~tpu_composer.scheduler.placement.PlacementEngine` scores and
  picks host sets (fragmentation-aware bin-packing + ICI contiguity);
- :class:`~tpu_composer.scheduler.queue.SchedulerQueue` remembers who is
  waiting, at what priority, for what gang demand;
- :class:`~tpu_composer.scheduler.preemption.Preemptor` computes minimal
  victim sets when a high-priority demand cannot fit;
- :class:`~tpu_composer.scheduler.defrag.DefragPlanner` (driven separately
  by the DefragLoop runnable) proposes migrations that reassemble
  contiguous capacity.

``place()`` is the one entry point for fresh slice placements and returns a
:class:`Placement` that either names the hosts (success), names the victims
the caller must evict first (preemption), or raises
:class:`~tpu_composer.scheduler.placement.AllocationError` (queue and
retry). The caller is expected to serialize calls (the request controller's
allocation lock) — the queue itself is thread-safe, but two concurrent
placements would double-book capacity exactly as the inline allocator
would have.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List, Optional, Set

from tpu_composer.api.types import ComposabilityRequest
from tpu_composer.runtime.metrics import (
    scheduler_fragmentation_score,
    scheduler_held_back_total,
    scheduler_queue_depth,
    scheduler_time_to_placement_seconds,
)
from tpu_composer.scheduler.defrag import DefragPlanner
from tpu_composer.scheduler.placement import AllocationError, PlacementEngine
from tpu_composer.scheduler.preemption import Preemptor
from tpu_composer.scheduler.queue import PendingEntry, SchedulerQueue
from tpu_composer.topology.slices import SliceShape


@dataclass
class Placement:
    """Outcome of a placement decision: hosts to use, or victims to evict
    first (mutually exclusive — victims non-empty means no hosts yet)."""

    nodes: List[str] = field(default_factory=list)
    victims: List[str] = field(default_factory=list)


class ClusterScheduler:
    def __init__(self, store, defrag_mode: str = "delete") -> None:
        self.store = store
        self.engine = PlacementEngine(store)
        self.queue = SchedulerQueue()
        self.preemptor = Preemptor(store, self.engine)
        # THE allocation lock: the request controller serializes its
        # placement passes on it, and the defrag executor takes it around
        # each verify+delete — without the shared lock, defrag's capacity
        # re-verification could be invalidated by a concurrent placement
        # between its check and its delete, evicting a Running worker
        # with nowhere to re-land.
        self.alloc_lock = threading.Lock()
        # defrag_mode="migrate" (cmd/main's default with live migration
        # enabled) makes the executor emit evacuation marks the owners'
        # migration drivers act on make-before-break; "delete" keeps the
        # legacy delete/re-solve executor (escape hatch + direct tests).
        self.defrag = DefragPlanner(
            store, self.engine, queue=self.queue, lock=self.alloc_lock,
            mode=defrag_mode,
        )

    # ------------------------------------------------------------------
    def place(
        self,
        req: ComposabilityRequest,
        shape: SliceShape,
        quarantined: Set[str],
    ) -> Placement:
        """Arbitrated placement for a fresh slice allocation."""
        # One store pass, two views: `occupied` (every live claim — what
        # the gate and the fragmentation gauge must see) and `used` (minus
        # this request's own children — what its own picking must see).
        occupied, used = self.engine.capacity_maps(req.name)
        self.queue.prune(self.store)
        try:
            nodes = self.engine.pick_hosts(req, shape, quarantined, used=used)
        except AllocationError:
            self.queue.note_pending(req, shape.num_hosts, shape.chips_per_host)
            self._update_gauges(quarantined, occupied)
            victims = self.preemptor.compute_victims(
                req, shape, quarantined, used
            )
            if victims:
                return Placement(victims=victims)
            raise
        self._admit(
            req, {n: shape.chips_per_host for n in nodes}, occupied,
            quarantined, pending_demand=(shape.num_hosts, shape.chips_per_host),
        )
        return Placement(nodes=nodes)

    def place_scalar(
        self,
        req: ComposabilityRequest,
        count: int,
        existing,
        quarantined: Set[str],
    ) -> List[str]:
        """Arbitrated scalar (gpu/cxlmemory) placement: scalar devices
        consume the same per-host ports as slice workers, so they go
        through the same pending queue and backfill gate — a priority-0
        gpu request must not grab the last free port a feasible
        higher-priority slice is queued for. No preemption, though:
        evicting a gang for an independent device is never worth the
        disruption, and scalar requests themselves recover by waiting."""
        occupied, used = self.engine.capacity_maps(req.name)
        self.queue.prune(self.store)
        # Demand bookkeeping for the gate's feasibility probes: pinned /
        # samenode requests need ONE host with room for the DELTA
        # (anchored — growth can't move elsewhere); spread policies need
        # `count` hosts with one port each. The demand must be the delta,
        # not delta+held: probes run against the full `occupied` map,
        # which already counts the devices the request holds — adding
        # them again would double-count and make the gate call a
        # satisfiable anchored request 'unsatisfiable', dropping its
        # protection exactly when it needs it.
        res = req.spec.resource
        existing = list(existing)
        exclude: tuple = ()
        if res.target_node:
            anchor = res.target_node
            demand = (1, count)
        elif res.allocation_policy == "samenode":
            # One host must take the whole delta; a not-yet-anchored
            # request can still land anywhere (anchor "").
            anchor = existing[0] if existing else ""
            demand = (1, count)
        else:
            anchor = ""
            demand = (count, 1)
            if res.allocation_policy == "differentnode":
                # Growth can only land on UNUSED nodes; a probe counting
                # the request's own hosts would overreport feasibility.
                exclude = tuple(sorted(set(existing)))
        try:
            nodes = self.engine.pick_scalar_nodes(
                req, count, existing, quarantined, used=used
            )
        except AllocationError:
            self.queue.note_pending(req, *demand, anchor=anchor,
                                    exclude_nodes=exclude)
            self._update_gauges(quarantined, occupied)
            raise
        add: dict = {}
        for n in nodes:
            add[n] = add.get(n, 0) + 1
        self._admit(req, add, occupied, quarantined, pending_demand=demand,
                    anchor=anchor, exclude_nodes=exclude)
        return nodes

    def _admit(
        self,
        req: ComposabilityRequest,
        add,
        occupied,
        quarantined: Set[str],
        pending_demand,
        anchor: str = "",
        exclude_nodes: tuple = (),
    ) -> None:
        """Run the backfill gate over a tentative placement (`add`: node ->
        ports it would consume) against the FULL occupancy map — including
        the placer's own holdings, or a grow onto a contended host reads
        as free and slips the gate. On pass, dequeue + record wait
        metrics; on hold raise AllocationError naming the protected
        entry."""
        held = self._gate(req, add, occupied, quarantined)
        if held is not None:
            self.queue.note_pending(req, *pending_demand, anchor=anchor,
                                    exclude_nodes=exclude_nodes)
            scheduler_held_back_total.inc()
            self._update_gauges(quarantined, occupied)
            raise AllocationError(
                f"held back: pending request {held.name} (priority"
                f" {held.priority} > {req.spec.priority}) needs this"
                " capacity"
            )
        wait = self.queue.note_placed(req.name)
        if wait is not None:
            scheduler_time_to_placement_seconds.observe(
                wait, type=req.spec.resource.type
            )
        self._update_gauges(quarantined, occupied)

    def place_extra(
        self,
        req: ComposabilityRequest,
        shape: SliceShape,
        exclude: Set[str],
        count: int,
        quarantined: Set[str],
    ) -> List[str]:
        """Grow-path placement for the delta workers of a live slice. Not
        gated: the slice already holds its capacity and a live resize must
        not deadlock behind the queue — arbitration happened at admission."""
        return self.engine.pick_slice_hosts(
            req, shape, exclude=exclude, count=count, quarantined=quarantined
        )

    def forget(self, name: str) -> None:
        """Drop a request from the pending queue (deletion path)."""
        self.queue.forget(name)
        scheduler_queue_depth.set(float(self.queue.depth()))

    def requeue(self, req: ComposabilityRequest, num_hosts: int,
                chips_per_host: int) -> None:
        """Re-register a request whose placement was granted but whose
        execution (fabric reservation) failed — the gate protection must
        come back before the backoff retry, and the depth gauge with it.
        (The time-to-placement sample observed at grant time stands; the
        residual wait is re-measured from here.)"""
        self.queue.note_pending(req, num_hosts, chips_per_host)
        scheduler_queue_depth.set(float(self.queue.depth()))

    # ------------------------------------------------------------------
    def _gate(
        self,
        req: ComposabilityRequest,
        add,
        occupied,
        quarantined: Set[str],
    ) -> Optional[PendingEntry]:
        """Conservative backfill: block this placement only if it would
        turn a currently-placeable higher-priority pending request into an
        unplaceable one. Probes run against the FULL occupancy map plus
        the tentative placement. Returns the entry being protected, or
        None."""
        entries = self.queue.entries_above(req.spec.priority)
        if not entries:
            return None
        after = dict(occupied)
        for n, chips in add.items():
            after[n] = after.get(n, 0) + chips
        # One node snapshot for all probes (2 per entry) this gate runs.
        nodes = self.engine.schedulable_nodes(quarantined)
        for entry in entries:
            if entry.name == req.name:
                continue
            other = self.store.try_get(ComposabilityRequest, entry.name)
            if other is None or other.being_deleted:
                continue
            feasible_now = self.engine.demand_feasible(
                other, entry.num_hosts, entry.chips_per_host, quarantined,
                occupied, anchor=entry.anchor, nodes=nodes,
                exclude_nodes=entry.exclude_nodes,
            )
            if not feasible_now:
                # Unsatisfiable either way (e.g. its only hosts are
                # quarantined) — holding everyone behind it would be
                # priority inversion for nothing.
                continue
            if not self.engine.demand_feasible(
                other, entry.num_hosts, entry.chips_per_host, quarantined,
                after, anchor=entry.anchor, nodes=nodes,
                exclude_nodes=entry.exclude_nodes,
            ):
                return entry
        return None

    def _update_gauges(self, quarantined: Set[str], occupied) -> None:
        # The gauge must reflect the REAL cluster: `occupied` is the full
        # occupancy map from the pass's single store scan (the
        # request-excluded picking view would read a resizing request's
        # attached chips as free and make the score flap).
        scheduler_queue_depth.set(float(self.queue.depth()))
        scheduler_fragmentation_score.set(
            self.engine.fragmentation(quarantined, occupied)
        )
