"""Defragmentation planner — reassemble contiguous TPU capacity.

Long-running fleets fragment: single-host slices land, die, and re-land
until every host holds a couple of chips and no 2-host gang can compose
even though the totals say it should. The planner proposes **worker
migrations** that vacate nearly-empty hosts by repacking their sub-host
chip groups onto already-fragmented peers — the same tightest-fit objective
the placement engine scores, run in reverse over live placements.

Safety properties:

- ``plan()`` is a pure dry run: it reads the store, simulates, and returns
  a :class:`DefragPlan`; nothing moves until ``execute()`` is called with
  that plan (and the operator can run plan-only forever via
  ``TPUC_DEFRAG_EXECUTE=0``).
- only members of **single-host**, **Running** slices whose owner allows
  disruption (``preemptionPolicy != Never``) migrate — moving one worker of
  a multi-host gang would invalidate its ICI topology mid-flight;
- execution goes through the existing resize machinery: the migrated
  member's ComposableResource is deleted, its owner re-enters
  NodeAllocating, and the placement engine's tightest-fit scoring lands the
  re-solve on the packed target (the plan records the predicted target and
  ``execute`` re-verifies it still fits before touching anything);
- a plan is idempotent: once executed and settled, the next ``plan()``
  finds no migration that improves the fragmentation score and returns
  empty.
"""

from __future__ import annotations

import contextlib
import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from tpu_composer.agent.publisher import quarantined_nodes
from tpu_composer.api.types import (
    ComposabilityRequest,
    ComposableResource,
    LABEL_MANAGED_BY,
    Node,
    PREEMPT_NEVER,
    REQUEST_STATE_RUNNING,
)
from tpu_composer.runtime.events import EventRecorder
from tpu_composer.runtime.metrics import (
    scheduler_defrag_migrations_total,
    scheduler_fragmentation_score,
)
from tpu_composer.runtime.store import NotFoundError, StoreError


@dataclass(frozen=True)
class Migration:
    request: str
    resource: str
    from_node: str
    to_node: str
    chips: int


@dataclass
class DefragPlan:
    migrations: List[Migration] = field(default_factory=list)
    frag_before: float = 0.0
    frag_after: float = 0.0

    @property
    def empty(self) -> bool:
        return not self.migrations


class DefragPlanner:
    def __init__(self, store, engine, queue=None, lock=None) -> None:
        self.store = store
        self.engine = engine
        # The scheduler's pending queue, when wired (ClusterScheduler
        # does): execute() refuses migrations whose owner's re-placement
        # the backfill gate would hold back — without this, a "capacity
        # shuffle" can silently turn into an unaccounted preemption.
        self.queue = queue
        # The scheduler's allocation lock, when wired: each migration's
        # verify+delete runs under it so a concurrent placement can't
        # fill the verified target between the check and the delete.
        self.lock = lock
        self.log = logging.getLogger("DefragPlanner")

    # ------------------------------------------------------------------
    def plan(self, quarantined: Optional[Set[str]] = None) -> DefragPlan:
        """Dry-run: the migrations that would vacate hosts and lower the
        fragmentation score, or an empty plan when none would."""
        if quarantined is None:
            quarantined = quarantined_nodes(self.store)
        used = self.engine.used_slots_map()
        frag_before = self.engine.fragmentation(quarantined, used)

        nodes: Dict[str, Node] = {
            n.metadata.name: n
            for n in self.store.list(Node)
            if n.status.ready
            and not n.spec.unschedulable
            and n.metadata.name not in quarantined
        }
        movable, anchored = self._occupants(nodes)

        # Vacate candidates: hosts with movable occupants and nothing
        # anchoring them, emptiest first (fewest chips to relocate per
        # host freed). Whether a host's entire occupancy is still movable
        # is re-checked against sim_used inside the loop: an earlier
        # migration may have packed chips ONTO a later candidate, and
        # "vacating" only its original occupants would be pure churn.
        sources = sorted(
            (
                name
                for name, node in nodes.items()
                if movable.get(name) and name not in anchored
            ),
            key=lambda name: (used.get(name, 0), name),
        )

        sim_used = dict(used)
        migrations: List[Migration] = []
        vacated: Set[str] = set()
        for src in sources:
            if sim_used.get(src, 0) != sum(
                m.chips for m in movable.get(src, [])
            ):
                continue  # received migrated chips (or was empty) — skip
            trial: List[Migration] = []
            trial_used = dict(sim_used)
            ok = True
            # Largest groups first: best-fit-decreasing packs tighter.
            for mig in sorted(
                movable.get(src, []), key=lambda m: (-m.chips, m.resource)
            ):
                target = self._best_target(
                    mig.chips, src, nodes, trial_used, vacated
                )
                if target is None:
                    ok = False
                    break
                trial.append(
                    Migration(
                        request=mig.request,
                        resource=mig.resource,
                        from_node=src,
                        to_node=target,
                        chips=mig.chips,
                    )
                )
                trial_used[target] = trial_used.get(target, 0) + mig.chips
                trial_used[src] = trial_used.get(src, 0) - mig.chips
            if ok and trial:
                migrations.extend(trial)
                sim_used = trial_used
                vacated.add(src)

        frag_after = self.engine.fragmentation(quarantined, sim_used)
        if frag_after >= frag_before:
            return DefragPlan([], frag_before, frag_before)
        return DefragPlan(migrations, frag_before, frag_after)

    def _best_target(
        self,
        chips: int,
        src: str,
        nodes: Dict[str, Node],
        sim_used: Dict[str, int],
        vacated: Set[str],
    ) -> Optional[str]:
        """Tightest-fit target that is already partially used — migrating
        onto an empty host would only move the fragmentation around."""
        best = None
        for name, node in nodes.items():
            if name == src or name in vacated:
                continue
            u = sim_used.get(name, 0)
            free = node.status.tpu_slots - u
            if u <= 0 or free < chips:
                continue
            key = (free - chips, name)
            if best is None or key < best[0]:
                best = (key, name)
        return best[1] if best else None

    def _occupants(self, nodes: Dict[str, Node]):
        """Split live TPU chip groups into movable (single-host Running
        slice, disruption allowed, sub-host group) vs anchoring (everything
        else pins its host in place)."""
        requests = {r.name: r for r in self.store.list(ComposabilityRequest)}
        movable: Dict[str, List[Migration]] = {}
        anchored: Set[str] = set()
        for c in self.store.list(ComposableResource):
            if c.being_deleted:
                continue
            node = c.spec.target_node
            if node not in nodes:
                continue
            owner = requests.get(c.metadata.labels.get(LABEL_MANAGED_BY, ""))
            if (
                c.spec.type == "tpu"
                and owner is not None
                and not owner.being_deleted
                and owner.spec.preemption_policy != PREEMPT_NEVER
                and owner.spec.resource.target_node == ""
                and owner.status.state == REQUEST_STATE_RUNNING
                and owner.status.slice.num_hosts == 1
                and c.spec.chip_count < nodes[node].status.tpu_slots
            ):
                movable.setdefault(node, []).append(
                    Migration(
                        request=owner.name,
                        resource=c.name,
                        from_node=node,
                        to_node="",
                        chips=c.spec.chip_count,
                    )
                )
            else:
                anchored.add(node)
        return movable, anchored

    # ------------------------------------------------------------------
    def execute(
        self, plan: DefragPlan, recorder: Optional[EventRecorder] = None
    ) -> int:
        """Drive a dry-run plan through the existing resize machinery:
        delete each migrated member so its owner re-solves onto the packed
        target. Re-verifies every migration against fresh state — a stale
        entry (child gone, target filled up meanwhile) is skipped, not
        forced — and runs each verify+delete under the scheduler's
        allocation lock (when wired) so a concurrent placement cannot fill
        the verified target between the check and the delete. Returns the
        number of migrations actually started."""
        started = 0
        quarantined = quarantined_nodes(self.store)
        for m in plan.migrations:
            with self.lock if self.lock is not None else contextlib.nullcontext():
                if self._execute_one(m, quarantined, recorder):
                    started += 1
        return started

    def _execute_one(
        self,
        m: Migration,
        quarantined,
        recorder: Optional[EventRecorder],
    ) -> bool:
        """One migration's verify+delete (caller holds the allocation
        lock when one is wired). False = skipped or failed."""
        child = self.store.try_get(ComposableResource, m.resource)
        if (
            child is None
            or child.being_deleted
            or child.spec.target_node != m.from_node
            or child.metadata.labels.get(LABEL_MANAGED_BY) != m.request
        ):
            return False  # world moved on since the plan was cut
        target = self.store.try_get(Node, m.to_node)
        used = self.engine.used_slots_map()
        if (
            target is None
            or not target.status.ready
            or target.spec.unschedulable
            or m.to_node in quarantined
            or target.status.tpu_slots - used.get(m.to_node, 0) < m.chips
        ):
            # Includes a target quarantined since the plan was cut: the
            # owner's re-solve would exclude it, so deleting the worker
            # could strand a Running slice with nowhere to re-land.
            return False
        if self._owner_would_be_held_back(m, used, quarantined):
            self.log.info(
                "defrag skip %s (%s -> %s): owner %s would be gate-"
                "blocked from re-placing behind a pending higher-"
                "priority demand", m.resource, m.from_node, m.to_node,
                m.request,
            )
            return False
        try:
            self.store.delete(ComposableResource, m.resource)
        except NotFoundError:
            return False
        except StoreError as e:
            self.log.warning(
                "defrag migration of %s (%s -> %s) failed: %s",
                m.resource, m.from_node, m.to_node, e,
            )
            return False
        scheduler_defrag_migrations_total.inc()
        if recorder is not None:
            req = self.store.try_get(ComposabilityRequest, m.request)
            if req is not None:
                recorder.event(
                    req, "Normal", "DefragMigration",
                    f"migrating worker {m.resource} "
                    f"{m.from_node} -> {m.to_node} to defragment capacity",
                )
        return True

    def _owner_would_be_held_back(
        self, m: Migration, used, quarantined
    ) -> bool:
        """Simulate the migration landing (from -= chips, to += chips) and
        run the same conservative-backfill probes the owner's re-solve
        will face: if a currently-feasible higher-priority pending demand
        becomes infeasible, the owner would be held back — the migration
        would evict a Running worker with nowhere to go."""
        if self.queue is None:
            return False
        owner = self.store.try_get(ComposabilityRequest, m.request)
        if owner is None or owner.being_deleted:
            return True  # nothing to re-place; skip the no-op delete
        entries = self.queue.entries_above(owner.spec.priority)
        if not entries:
            return False
        after = dict(used)
        after[m.from_node] = after.get(m.from_node, 0) - m.chips
        after[m.to_node] = after.get(m.to_node, 0) + m.chips
        nodes = self.engine.schedulable_nodes(quarantined)
        for entry in entries:
            other = self.store.try_get(ComposabilityRequest, entry.name)
            if other is None or other.being_deleted:
                continue
            if self.engine.demand_feasible(
                other, entry.num_hosts, entry.chips_per_host, quarantined,
                used, anchor=entry.anchor, nodes=nodes,
                exclude_nodes=entry.exclude_nodes,
            ) and not self.engine.demand_feasible(
                other, entry.num_hosts, entry.chips_per_host, quarantined,
                after, anchor=entry.anchor, nodes=nodes,
                exclude_nodes=entry.exclude_nodes,
            ):
                return True
        return False


class DefragLoop:
    """Manager runnable: periodically plan (always) and execute (opt-in).

    Plan-only mode still updates the fragmentation gauge and logs the
    migrations it *would* run — the operator preview the ISSUE asks for."""

    def __init__(
        self,
        store,
        planner: DefragPlanner,
        period: float = 300.0,
        execute: bool = False,
        recorder: Optional[EventRecorder] = None,
        gate: Optional[Callable[[], bool]] = None,
    ) -> None:
        self.store = store
        self.planner = planner
        self.period = period
        self.execute = execute
        self.recorder = recorder
        # Singleton gate for sharded deployments: defrag plans over the
        # WHOLE cluster, so N replicas running it concurrently would
        # compute mutually unaware, conflicting migration sets. cmd/main
        # gates the pass on owning shard 0 — exactly one replica defrags
        # at a time, and the duty fails over with the shard lease. None
        # (unsharded) runs every tick, today's behavior.
        self.gate = gate
        self.log = logging.getLogger("DefragLoop")

    def __call__(self, stop_event: threading.Event) -> None:
        while not stop_event.wait(self.period):
            if self.gate is not None and not self.gate():
                continue  # another replica holds the defrag duty
            try:
                self.run_once()
            except StoreError as e:  # pragma: no cover - wire-store only
                self.log.warning("defrag pass failed: %s", e)

    def run_once(self) -> DefragPlan:
        plan = self.planner.plan()
        # Gauge reflects the CURRENT cluster, not the plan's prediction —
        # execution is asynchronous (owners re-solve on their own clock).
        scheduler_fragmentation_score.set(plan.frag_before)
        if plan.empty:
            return plan
        summary = ", ".join(
            f"{m.resource}:{m.from_node}->{m.to_node}" for m in plan.migrations
        )
        if self.execute:
            n = self.planner.execute(plan, recorder=self.recorder)
            self.log.info(
                "defrag executed %d/%d migration(s) (frag %.3f -> %.3f): %s",
                n, len(plan.migrations), plan.frag_before, plan.frag_after,
                summary,
            )
        else:
            self.log.info(
                "defrag dry-run: %d migration(s) would cut fragmentation"
                " %.3f -> %.3f: %s",
                len(plan.migrations), plan.frag_before, plan.frag_after,
                summary,
            )
        return plan
