"""Defragmentation planner — reassemble contiguous TPU capacity.

Long-running fleets fragment: single-host slices land, die, and re-land
until every host holds a couple of chips and no 2-host gang can compose
even though the totals say it should. The planner proposes **worker
migrations** that vacate nearly-empty hosts by repacking their sub-host
chip groups onto already-fragmented peers — the same tightest-fit objective
the placement engine scores, run in reverse over live placements.

Safety properties:

- ``plan()`` is a pure dry run: it reads the store, simulates, and returns
  a :class:`DefragPlan`; nothing moves until ``execute()`` is called with
  that plan (and the operator can run plan-only forever via
  ``TPUC_DEFRAG_EXECUTE=0``).
- only members of **single-host**, **Running** slices whose owner allows
  disruption (``preemptionPolicy != Never``) migrate — moving one worker of
  a multi-host gang would invalidate its ICI topology mid-flight;
- execution has two modes. ``mode="migrate"`` (cmd/main's default with the
  live-migration verb enabled) never deletes anything: each verified
  migration is handed to the owner's migration driver as a durable
  evacuation mark (``tpu.composer.dev/evacuate=defrag`` plus the verified
  target as a hint), and the member moves make-before-break — defrag is
  safe to run with ``--defrag-execute`` against live workloads.
  ``mode="delete"`` is the legacy shape (and the TPUC_MIGRATE=0 escape
  hatch): the member's ComposableResource is deleted, its owner re-enters
  NodeAllocating, and the placement engine's tightest-fit scoring lands the
  re-solve on the packed target. Both modes re-verify the plan against
  fresh state before touching anything;
- planning is gated on MIGRATABILITY in migrate mode: a request whose
  ``repairPolicy`` is ``None`` has opted out of the replacement machinery
  migration rides on, so its members anchor their hosts; and an open
  repair/migration breaker skips the pass entirely (evacuating through a
  brownout is how outages amplify). Skip reasons are tallied into
  ``last_report`` and served by the manager's ``/debug/defrag`` endpoint;
- a plan is idempotent: once executed and settled, the next ``plan()``
  finds no migration that improves the fragmentation score and returns
  empty.
"""

from __future__ import annotations

import contextlib
import logging
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from tpu_composer.agent.publisher import quarantined_nodes
from tpu_composer.api.meta import now_iso
from tpu_composer.api.types import (
    ANNOTATION_EVACUATE,
    ANNOTATION_EVACUATE_TARGET,
    ComposabilityRequest,
    ComposableResource,
    LABEL_MANAGED_BY,
    MIGRATE_TRIGGER_DEFRAG,
    Node,
    PREEMPT_NEVER,
    REPAIR_NONE,
    REQUEST_STATE_RUNNING,
    RESOURCE_STATE_ONLINE,
)
from tpu_composer.runtime.events import EventRecorder
from tpu_composer.runtime.metrics import (
    migration_breaker_open,
    repair_breaker_open,
    scheduler_defrag_migrations_total,
    scheduler_fragmentation_score,
)
from tpu_composer.runtime.store import ConflictError, NotFoundError, StoreError


@dataclass(frozen=True)
class Migration:
    request: str
    resource: str
    from_node: str
    to_node: str
    chips: int


@dataclass
class DefragPlan:
    migrations: List[Migration] = field(default_factory=list)
    frag_before: float = 0.0
    frag_after: float = 0.0
    #: Why candidates were excluded from THIS plan, reason -> count —
    #: carried on the plan itself so a report pairs migrations and skips
    #: from the same pass (the shared last_skips is only the latest
    #: complete snapshot, which a concurrent pass may have replaced).
    skips: Dict[str, int] = field(default_factory=dict)

    @property
    def empty(self) -> bool:
        return not self.migrations


class DefragPlanner:
    def __init__(self, store, engine, queue=None, lock=None,
                 mode: str = "delete", decision_ledger=None) -> None:
        self.store = store
        self.engine = engine
        # The scheduler's DecisionLedger, when wired: planner skips and
        # executed migrations land in the owners' decision rings so
        # "why is defrag not consolidating my worker" / "why did my
        # worker move" answer themselves via /debug/scheduler/explain.
        # None (TPUC_DECISIONS=0, or direct construction) records nothing.
        self.decision_ledger = decision_ledger
        # The scheduler's pending queue, when wired (ClusterScheduler
        # does): execute() refuses migrations whose owner's re-placement
        # the backfill gate would hold back — without this, a "capacity
        # shuffle" can silently turn into an unaccounted preemption.
        self.queue = queue
        # The scheduler's allocation lock, when wired: each migration's
        # verify+delete runs under it so a concurrent placement can't
        # fill the verified target between the check and the delete.
        self.lock = lock
        # Execution mode: "migrate" hands each verified migration to the
        # owner's live-migration driver (durable evacuation mark + target
        # hint — make-before-break, safe against live jobs); "delete" is
        # the legacy delete/re-solve shape kept for the TPUC_MIGRATE=0
        # escape hatch and direct-construction tests.
        self.mode = mode
        # Why candidates were excluded from the last plan(), reason ->
        # count — the /debug/defrag dry-run report's substance (a planner
        # that silently plans nothing is indistinguishable from a healthy
        # defragmented fleet without this). Each plan() tallies into a
        # LOCAL dict and publishes it whole at the end, so a /debug/defrag
        # dry-run racing the periodic loop's pass can never blend the two
        # passes' counts (last complete snapshot wins).
        self.last_skips: Dict[str, int] = {}
        self.log = logging.getLogger("DefragPlanner")

    # ------------------------------------------------------------------
    def plan(self, quarantined: Optional[Set[str]] = None) -> DefragPlan:
        """Dry-run: the migrations that would vacate hosts and lower the
        fragmentation score, or an empty plan when none would."""
        if quarantined is None:
            quarantined = quarantined_nodes(self.store)
        used = self.engine.used_slots_map()
        frag_before = self.engine.fragmentation(quarantined, used)

        nodes: Dict[str, Node] = {
            n.metadata.name: n
            for n in self.store.list(Node)
            if n.status.ready
            and not n.spec.unschedulable
            and n.metadata.name not in quarantined
        }
        skips: Dict[str, int] = {}
        skip_owners: Dict[str, Dict[str, str]] = {}
        movable, anchored = self._occupants(nodes, skips, skip_owners)

        # Vacate candidates: hosts with movable occupants and nothing
        # anchoring them, emptiest first (fewest chips to relocate per
        # host freed). Whether a host's entire occupancy is still movable
        # is re-checked against sim_used inside the loop: an earlier
        # migration may have packed chips ONTO a later candidate, and
        # "vacating" only its original occupants would be pure churn.
        sources = sorted(
            (
                name
                for name, node in nodes.items()
                if movable.get(name) and name not in anchored
            ),
            key=lambda name: (used.get(name, 0), name),
        )

        sim_used = dict(used)
        migrations: List[Migration] = []
        vacated: Set[str] = set()
        for src in sources:
            if sim_used.get(src, 0) != sum(
                m.chips for m in movable.get(src, [])
            ):
                continue  # received migrated chips (or was empty) — skip
            trial: List[Migration] = []
            trial_used = dict(sim_used)
            ok = True
            # Largest groups first: best-fit-decreasing packs tighter.
            for mig in sorted(
                movable.get(src, []), key=lambda m: (-m.chips, m.resource)
            ):
                target = self._best_target(
                    mig.chips, src, nodes, trial_used, vacated
                )
                if target is None:
                    ok = False
                    break
                trial.append(
                    Migration(
                        request=mig.request,
                        resource=mig.resource,
                        from_node=src,
                        to_node=target,
                        chips=mig.chips,
                    )
                )
                trial_used[target] = trial_used.get(target, 0) + mig.chips
                trial_used[src] = trial_used.get(src, 0) - mig.chips
            if ok and trial:
                migrations.extend(trial)
                sim_used = trial_used
                vacated.add(src)

        frag_after = self.engine.fragmentation(quarantined, sim_used)
        self.last_skips = skips  # one atomic publish per completed plan
        self._record_skips(skip_owners)
        if frag_after >= frag_before:
            return DefragPlan([], frag_before, frag_before, skips=skips)
        return DefragPlan(migrations, frag_before, frag_after, skips=skips)

    def _record_skips(
        self, skip_owners: Dict[str, Dict[str, str]]
    ) -> None:
        """One defrag-skip decision per excluded OWNER per pass — the
        ledger collapses identical repeats across periodic passes, so a
        steady-state skip costs one record with a repeats counter."""
        if self.decision_ledger is None:
            return
        from tpu_composer.scheduler import ledger as ledger_mod

        for owner, members in sorted(skip_owners.items()):
            reasons = sorted(set(members.values()))
            self.decision_ledger.record(ledger_mod.DecisionRecord(
                request=owner,
                kind=ledger_mod.KIND_DEFRAG_SKIP,
                outcome=ledger_mod.OUTCOME_SKIPPED,
                binding={"resource": "defrag-migratability",
                         "members": members},
                summary=(
                    "defrag left member(s) in place:"
                    f" {', '.join(reasons)}"
                ),
            ))

    def _best_target(
        self,
        chips: int,
        src: str,
        nodes: Dict[str, Node],
        sim_used: Dict[str, int],
        vacated: Set[str],
    ) -> Optional[str]:
        """Tightest-fit target that is already partially used — migrating
        onto an empty host would only move the fragmentation around."""
        best = None
        for name, node in nodes.items():
            if name == src or name in vacated:
                continue
            u = sim_used.get(name, 0)
            free = node.status.tpu_slots - u
            if u <= 0 or free < chips:
                continue
            key = (free - chips, name)
            if best is None or key < best[0]:
                best = (key, name)
        return best[1] if best else None

    def _occupants(
        self,
        nodes: Dict[str, Node],
        skips: Dict[str, int],
        skip_owners: Optional[Dict[str, Dict[str, str]]] = None,
    ):
        """Split live TPU chip groups into movable (single-host Running
        slice, disruption allowed — and in migrate mode MIGRATABLE:
        ``repairPolicy != None``, since live migration rides the
        replacement machinery that policy opts out of — sub-host group) vs
        anchoring (everything else pins its host in place). Every
        exclusion tallies a reason into ``skips``."""
        requests = {r.name: r for r in self.store.list(ComposabilityRequest)}
        movable: Dict[str, List[Migration]] = {}
        anchored: Set[str] = set()
        for c in self.store.list(ComposableResource):
            if c.being_deleted:
                continue
            node = c.spec.target_node
            if node not in nodes:
                continue
            owner = requests.get(c.metadata.labels.get(LABEL_MANAGED_BY, ""))
            reason = self._immovable_reason(c, owner, nodes[node])
            if reason is None:
                movable.setdefault(node, []).append(
                    Migration(
                        request=owner.name,
                        resource=c.name,
                        from_node=node,
                        to_node="",
                        chips=c.spec.chip_count,
                    )
                )
            else:
                skips[reason] = skips.get(reason, 0) + 1
                anchored.add(node)
                if skip_owners is not None and owner is not None:
                    skip_owners.setdefault(owner.name, {})[c.name] = reason
        return movable, anchored

    def _immovable_reason(self, c, owner, node: Node) -> Optional[str]:
        """Why this chip group anchors its host (None = movable)."""
        if c.spec.type != "tpu":
            return "non-tpu"
        if owner is None or owner.being_deleted:
            return "no-live-owner"
        if owner.spec.preemption_policy == PREEMPT_NEVER:
            return "preemptionPolicy=Never"
        if owner.spec.resource.target_node:
            return "pinned-target-node"
        if owner.status.state != REQUEST_STATE_RUNNING:
            return "owner-not-running"
        if owner.status.slice.num_hosts != 1:
            return "multi-host-slice"
        if c.spec.chip_count >= node.status.tpu_slots:
            return "whole-host-group"
        if self.mode == "migrate":
            if owner.spec.repair_policy == REPAIR_NONE:
                # Live migration rides the replacement machinery;
                # repairPolicy=None opted this request out of it — the
                # planner must not propose moves nobody will execute.
                return "repairPolicy=None"
            if c.status.state not in (RESOURCE_STATE_ONLINE,):
                # Degraded/Repairing/Migrating members belong to the
                # repair or migration driver already in flight.
                return f"member-{c.status.state or 'pending'}"
            if c.metadata.annotations.get(ANNOTATION_EVACUATE):
                return "already-evacuating"
        return None

    # ------------------------------------------------------------------
    def execute(
        self, plan: DefragPlan, recorder: Optional[EventRecorder] = None
    ) -> int:
        """Start a dry-run plan's migrations. In ``migrate`` mode each
        verified entry becomes a durable evacuation mark (+ target hint)
        on the member — the owner's live-migration driver moves it
        make-before-break, so a Running workload never loses the member
        before its replacement is Online. In ``delete`` mode (legacy /
        escape hatch) the member is deleted and its owner re-solves onto
        the packed target. Either way every entry is re-verified against
        fresh state — a stale one (child gone, target filled up meanwhile)
        is skipped, not forced — under the scheduler's allocation lock
        (when wired) so a concurrent placement cannot fill the verified
        target between the check and the act. Returns the number of
        migrations actually started."""
        started = 0
        quarantined = quarantined_nodes(self.store)
        for m in plan.migrations:
            with self.lock if self.lock is not None else contextlib.nullcontext():
                if self._execute_one(m, quarantined, recorder):
                    started += 1
        return started

    def _execute_one(
        self,
        m: Migration,
        quarantined,
        recorder: Optional[EventRecorder],
    ) -> bool:
        """One migration's verify+delete (caller holds the allocation
        lock when one is wired). False = skipped or failed."""
        child = self.store.try_get(ComposableResource, m.resource)
        if (
            child is None
            or child.being_deleted
            or child.spec.target_node != m.from_node
            or child.metadata.labels.get(LABEL_MANAGED_BY) != m.request
        ):
            return False  # world moved on since the plan was cut
        target = self.store.try_get(Node, m.to_node)
        used = self.engine.used_slots_map()
        if (
            target is None
            or not target.status.ready
            or target.spec.unschedulable
            or m.to_node in quarantined
            or target.status.tpu_slots - used.get(m.to_node, 0) < m.chips
        ):
            # Includes a target quarantined since the plan was cut: the
            # owner's re-solve would exclude it, so deleting the worker
            # could strand a Running slice with nowhere to re-land.
            return False
        if self._owner_would_be_held_back(m, used, quarantined):
            self.log.info(
                "defrag skip %s (%s -> %s): owner %s would be gate-"
                "blocked from re-placing behind a pending higher-"
                "priority demand", m.resource, m.from_node, m.to_node,
                m.request,
            )
            return False
        if self.mode == "migrate":
            if (
                child.metadata.annotations.get(ANNOTATION_EVACUATE)
                or child.status.state != RESOURCE_STATE_ONLINE
            ):
                return False  # already moving (or not movable right now)
            child.metadata.annotations[ANNOTATION_EVACUATE] = (
                MIGRATE_TRIGGER_DEFRAG
            )
            child.metadata.annotations[ANNOTATION_EVACUATE_TARGET] = m.to_node
            try:
                self.store.update(child)
            except (ConflictError, NotFoundError):
                return False  # world moved on — re-planned next pass
            except StoreError as e:
                self.log.warning(
                    "defrag evacuation mark on %s (%s -> %s) failed: %s",
                    m.resource, m.from_node, m.to_node, e,
                )
                return False
        else:
            try:
                self.store.delete(ComposableResource, m.resource)
            except NotFoundError:
                return False
            except StoreError as e:
                self.log.warning(
                    "defrag migration of %s (%s -> %s) failed: %s",
                    m.resource, m.from_node, m.to_node, e,
                )
                return False
        scheduler_defrag_migrations_total.inc()
        if self.decision_ledger is not None:
            from tpu_composer.scheduler import ledger as ledger_mod

            self.decision_ledger.record(ledger_mod.DecisionRecord(
                request=m.request,
                kind=ledger_mod.KIND_DEFRAG_MIGRATE,
                outcome=ledger_mod.OUTCOME_EVACUATING,
                chosen=[m.to_node],
                tiebreak="tightest-fit consolidation target",
                summary=(
                    f"defrag {'evacuating' if self.mode == 'migrate' else 'migrating'}"
                    f" worker {m.resource}: {m.from_node} -> {m.to_node}"
                    f" ({m.chips} chips) to reassemble contiguous capacity"
                ),
            ))
        if recorder is not None:
            req = self.store.try_get(ComposabilityRequest, m.request)
            if req is not None:
                recorder.event(
                    req, "Normal", "DefragMigration",
                    f"migrating worker {m.resource} "
                    f"{m.from_node} -> {m.to_node} to defragment capacity"
                    + (" (live, make-before-break)"
                       if self.mode == "migrate" else ""),
                )
        return True

    def _owner_would_be_held_back(
        self, m: Migration, used, quarantined
    ) -> bool:
        """Simulate the migration landing (from -= chips, to += chips) and
        run the same conservative-backfill probes the owner's re-solve
        will face: if a currently-feasible higher-priority pending demand
        becomes infeasible, the owner would be held back — the migration
        would evict a Running worker with nowhere to go."""
        if self.queue is None:
            return False
        owner = self.store.try_get(ComposabilityRequest, m.request)
        if owner is None or owner.being_deleted:
            return True  # nothing to re-place; skip the no-op delete
        entries = self.queue.entries_above(owner.spec.priority)
        if not entries:
            return False
        after = dict(used)
        after[m.from_node] = after.get(m.from_node, 0) - m.chips
        after[m.to_node] = after.get(m.to_node, 0) + m.chips
        nodes = self.engine.schedulable_nodes(quarantined)
        for entry in entries:
            other = self.store.try_get(ComposabilityRequest, entry.name)
            if other is None or other.being_deleted:
                continue
            if self.engine.demand_feasible(
                other, entry.num_hosts, entry.chips_per_host, quarantined,
                used, anchor=entry.anchor, nodes=nodes,
                exclude_nodes=entry.exclude_nodes,
            ) and not self.engine.demand_feasible(
                other, entry.num_hosts, entry.chips_per_host, quarantined,
                after, anchor=entry.anchor, nodes=nodes,
                exclude_nodes=entry.exclude_nodes,
            ):
                return True
        return False


class DefragLoop:
    """Manager runnable: periodically plan (always) and execute (opt-in).

    Plan-only mode still updates the fragmentation gauge and logs the
    migrations it *would* run — the operator preview the ISSUE asks for."""

    def __init__(
        self,
        store,
        planner: DefragPlanner,
        period: float = 300.0,
        execute: bool = False,
        recorder: Optional[EventRecorder] = None,
        gate: Optional[Callable[[], bool]] = None,
    ) -> None:
        self.store = store
        self.planner = planner
        self.period = period
        self.execute = execute
        self.recorder = recorder
        # Singleton gate for sharded deployments: defrag plans over the
        # WHOLE cluster, so N replicas running it concurrently would
        # compute mutually unaware, conflicting migration sets. cmd/main
        # gates the pass on owning shard 0 — exactly one replica defrags
        # at a time, and the duty fails over with the shard lease. None
        # (unsharded) runs every tick, today's behavior.
        self.gate = gate
        # Last pass's report for /debug/defrag: what was planned, what was
        # skipped and why, whether a breaker froze the pass.
        self.last_report: Dict[str, object] = {}
        self.log = logging.getLogger("DefragLoop")

    def __call__(self, stop_event: threading.Event) -> None:
        while not stop_event.wait(self.period):
            if self.gate is not None and not self.gate():
                continue  # another replica holds the defrag duty
            try:
                self.run_once()
            except StoreError as e:  # pragma: no cover - wire-store only
                self.log.warning("defrag pass failed: %s", e)

    def _frozen(self) -> bool:
        """Migrate-mode planning is pointless (and planning THROUGH a
        brownout would be worse than pointless) while the repair or
        migration breaker is open — the migration driver would freeze
        every move anyway. Delete mode predates the breakers and keeps
        its legacy behavior."""
        if self.planner.mode != "migrate":
            return False
        return (
            repair_breaker_open.value() > 0
            or migration_breaker_open.value() > 0
        )

    def run_once(self) -> DefragPlan:
        if self._frozen():
            self.last_report = {
                "at": now_iso(),
                "mode": self.planner.mode,
                "execute": self.execute,
                "frozen": True,
                "skips": {"breaker-open": 1},
                "migrations": [],
            }
            self.log.info(
                "defrag pass skipped: repair/migration breaker open"
                " (brownout — no planning, no evacuation)"
            )
            return DefragPlan()
        plan = self.planner.plan()
        # Gauge reflects the CURRENT cluster, not the plan's prediction —
        # execution is asynchronous (owners re-solve on their own clock).
        scheduler_fragmentation_score.set(plan.frag_before)
        report: Dict[str, object] = {
            "at": now_iso(),
            "mode": self.planner.mode,
            # Which capacity accounting the plan read ("native"/"python" =
            # the watch-maintained snapshot, "legacy" = store walks) — a
            # stale-snapshot suspicion starts by checking this.
            "engine": getattr(
                self.planner.engine, "kernel_kind", "legacy"
            ),
            "execute": self.execute,
            "frozen": False,
            "frag_before": plan.frag_before,
            "frag_after": plan.frag_after,
            "skips": dict(plan.skips),
            "migrations": [
                {"request": m.request, "resource": m.resource,
                 "from": m.from_node, "to": m.to_node, "chips": m.chips}
                for m in plan.migrations
            ],
        }
        if plan.empty:
            self.last_report = report
            return plan
        summary = ", ".join(
            f"{m.resource}:{m.from_node}->{m.to_node}" for m in plan.migrations
        )
        if self.execute:
            n = self.planner.execute(plan, recorder=self.recorder)
            report["started"] = n
            self.log.info(
                "defrag executed %d/%d migration(s) via %s (frag %.3f ->"
                " %.3f): %s",
                n, len(plan.migrations), self.planner.mode,
                plan.frag_before, plan.frag_after, summary,
            )
        else:
            self.log.info(
                "defrag dry-run: %d migration(s) would cut fragmentation"
                " %.3f -> %.3f: %s",
                len(plan.migrations), plan.frag_before, plan.frag_after,
                summary,
            )
        self.last_report = report
        return plan

    def report(self) -> Dict[str, object]:
        """The /debug/defrag payload: a FRESH dry-run plan (never
        executed, whatever --defrag-execute says) alongside the last
        periodic pass's record."""
        if self._frozen():
            return {
                "mode": self.planner.mode,
                "execute": self.execute,
                "frozen": True,
                "dry_run": {"migrations": [], "skips": {"breaker-open": 1}},
                "last_pass": self.last_report,
            }
        plan = self.planner.plan()
        return {
            "mode": self.planner.mode,
            "engine": getattr(
                self.planner.engine, "kernel_kind", "legacy"
            ),
            "execute": self.execute,
            "frozen": False,
            "dry_run": {
                "frag_before": plan.frag_before,
                "frag_after": plan.frag_after,
                "migrations": [
                    {"request": m.request, "resource": m.resource,
                     "from": m.from_node, "to": m.to_node, "chips": m.chips}
                    for m in plan.migrations
                ],
                "skips": dict(plan.skips),
            },
            "last_pass": self.last_report,
        }
