"""Decision ledger — every scheduler decision explains itself.

Three observability layers made the control plane's *mechanics* legible —
traces say where the time went, profiles say where the CPU went, SLO burn
says whether the promise holds — but the scheduler's *decisions* stayed
opaque: "where did my slice land", "why is it still queued", "why was that
victim preempted" had no answer beyond unlabeled aggregate counters. The
32-GPU composable-system study (arXiv:2404.06467) evaluates exactly these
quantities as curves, and per-tenant accounting (Funky, arXiv:2510.15755)
presumes a substrate that can attribute every placement — this module is
that substrate.

Every admit / place / hold-back / preempt / defrag decision the
:class:`~tpu_composer.scheduler.core.ClusterScheduler` (and the
DefragPlanner) makes emits a structured :class:`DecisionRecord`:

- an **inputs digest**: free chips per node, fragmentation score, the
  quarantine set and pending-queue depth the decision saw;
- the **candidates considered**, each with a per-node verdict ("ok",
  "quarantined", "no-tpu-ports free=1 need=4", ...);
- the **chosen hosts** with the tiebreak rationale (tightest-fit leftover
  sum, ICI contiguity window span);
- the **victims** with the minimality rationale (exhaustive vs
  greedy+prune search, candidate pool size);
- for hold-backs, the **binding constraint**: which resource is short and
  by how much (tpu-ports 3 hosts short; backfill-gate protecting X).

Records live in a bounded per-CR ring (LRU-capped object map — a churning
fleet cannot grow the heap), the latest record's one-line summary surfaces
as a Queued / Placed / Preempting controller Event (deduped: a reconcile
retry that reaches the identical decision bumps a ``repeats`` counter
instead of appending), ``/debug/scheduler/explain/<name>`` serves the ring
as JSON, and ``python -m tpu_composer explain <cr>`` prints it from a
terminal. Decision ids double as trace ids: the decision span hands one
flow per planned worker to the resource controller's intent mint
(:meth:`DecisionLedger.link_decision`, via the controller's explicit
ledger handle), so one Perfetto flow runs decision → attach → Ready on
the intent-nonce trace machinery.

``TPUC_DECISIONS=0`` (cmd/main ``--no-decisions``) constructs none of
this: the scheduler's ledger handle is None and no record, verdict scan or
event is ever built — the perf-smoke gate holds the enabled path within 5%
of that on the 32-chip wave.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tpu_composer.api.meta import now_iso
from tpu_composer.runtime import tracing
from tpu_composer.runtime.metrics import scheduler_decisions_total

log = logging.getLogger("decisions")

#: The most recently constructed ledger (crash-hook dump target +
#: the resource controller's decision→attach join point), like the
#: profiler / SLO engine / fleet plane actives.
_active: Optional["DecisionLedger"] = None

#: Decision kinds (the ledger's vocabulary; OPERATIONS.md documents it).
KIND_PLACE = "place"
KIND_PLACE_SCALAR = "place-scalar"
KIND_PLACE_EXTRA = "place-extra"
KIND_DEFRAG_SKIP = "defrag-skip"
KIND_DEFRAG_MIGRATE = "defrag-migrate"

OUTCOME_PLACED = "placed"
OUTCOME_HELD_BACK = "held-back"
OUTCOME_PREEMPTING = "preempting"
OUTCOME_SKIPPED = "skipped"
OUTCOME_EVACUATING = "evacuating"

#: How many candidate verdicts a record keeps. The ledger owns this
#: truncation policy, and the scheduler passes it DOWN into the engine's
#: verdict scan (``candidate_verdicts(..., cap=CANDIDATE_CAP)``) so only
#: this many per-node dicts are ever materialized — truncating after a
#: full O(nodes) materialization was half the decision-plane overhead
#: BENCH_r10 measured.
CANDIDATE_CAP = 64


@dataclass
class DecisionRecord:
    """One scheduler decision, self-describing."""

    request: str
    kind: str
    outcome: str
    #: one-line human summary — what the Event carries and the triage
    #: runbook greps for.
    summary: str
    decision_id: str = ""
    seq: int = 0
    at: str = ""
    priority: int = 0
    #: the demand being decided: {"num_hosts": N, "chips_per_host": C}
    demand: Dict[str, int] = field(default_factory=dict)
    #: inputs digest: what the decision saw (free chips per node,
    #: fragmentation, quarantine set, pending-queue depth).
    inputs: Dict[str, Any] = field(default_factory=dict)
    #: candidates considered: [{"node", "free", "verdict"}, ...]
    candidates: List[Dict[str, Any]] = field(default_factory=list)
    chosen: List[str] = field(default_factory=list)
    #: why THESE hosts among the candidates (tightest-fit sum, ICI span).
    tiebreak: str = ""
    victims: List[str] = field(default_factory=list)
    #: why THIS victim set is minimal (search mode, pool size).
    victim_rationale: str = ""
    #: hold-backs only: the binding constraint — which resource, how short.
    binding: Dict[str, Any] = field(default_factory=dict)
    #: identical consecutive decisions collapse into one record (reconcile
    #: retries reach the same verdict every few seconds while queued).
    repeats: int = 1
    #: monotonic instant of the last FULL record()/collapse (bumps do not
    #: advance it) — the rescan rate-limit's anchor, so repeat hold-backs
    #: re-derive their binding shortfall at most once per window instead
    #: of sliding the window forever on stale data. Never serialized.
    mono: float = field(default=0.0, repr=False)
    #: attach intents that executed this decision (filled by
    #: :func:`link_decision` as the resource controller mints them).
    nonces: List[str] = field(default_factory=list)
    #: pending Perfetto flow handles for the decision → attach arrows
    #: (one per planned worker); consumed by link_decision, never
    #: serialized.
    flows: List[tracing.TraceContext] = field(default_factory=list, repr=False)

    def to_doc(self) -> Dict[str, Any]:
        doc: Dict[str, Any] = {
            "decision_id": self.decision_id,
            "seq": self.seq,
            "at": self.at,
            "request": self.request,
            "kind": self.kind,
            "outcome": self.outcome,
            "priority": self.priority,
            "summary": self.summary,
            "repeats": self.repeats,
        }
        if self.demand:
            doc["demand"] = dict(self.demand)
        if self.inputs:
            doc["inputs"] = dict(self.inputs)
        if self.candidates:
            doc["candidates"] = list(self.candidates)
        if self.chosen:
            doc["chosen"] = list(self.chosen)
        if self.tiebreak:
            doc["tiebreak"] = self.tiebreak
        if self.victims:
            doc["victims"] = list(self.victims)
        if self.victim_rationale:
            doc["victim_rationale"] = self.victim_rationale
        if self.binding:
            doc["binding"] = dict(self.binding)
        if self.nonces:
            doc["nonces"] = list(self.nonces)
        return doc


class _EventRef:
    """Recorder shim so the ledger can event against a CR by name without
    holding the (possibly re-read) object."""

    KIND = "ComposabilityRequest"

    def __init__(self, name: str) -> None:
        from types import SimpleNamespace

        self.metadata = SimpleNamespace(name=name)


class DecisionLedger:
    """Bounded per-CR decision rings + the hold-back reason tally.

    Thread-safety: record() is called under the scheduler's allocation
    lock for placement decisions and from the defrag loop for defrag ones;
    the internal lock makes the ledger safe either way (the explain
    endpoint reads from the health-server thread)."""

    #: Event reasons by outcome — the "latest record surfaces as an Event"
    #: contract. Preempting rides the controller's own per-victim events;
    #: the ledger's copy carries the WHY (candidates, minimality).
    _EVENT_REASONS = {
        OUTCOME_PLACED: ("Normal", "Placed"),
        OUTCOME_HELD_BACK: ("Warning", "Queued"),
        OUTCOME_PREEMPTING: ("Normal", "Preempting"),
    }

    #: A repeat hold-back within this many seconds of the latest matching
    #: record skips the full candidate/inputs rescan (bump_if_recent):
    #: a queued request's backoff retries must not pay O(nodes) scans
    #: under the allocation lock per tick just to collapse into a counter.
    hold_rescan_s = 2.0

    def __init__(
        self,
        per_object: int = 32,
        max_objects: int = 2048,
        recorder=None,  # duck-typed EventRecorder (.event); None = no events
        recent_holds: int = 256,
    ) -> None:
        global _active
        self._lock = threading.Lock()
        self._per_object = per_object
        self._max_objects = max_objects
        self.recorder = recorder
        self._seq = 0
        # name -> deque[DecisionRecord], LRU-ordered like the flight
        # recorder's object map.
        self._objects: "collections.OrderedDict[str, collections.deque]" = (
            collections.OrderedDict()
        )
        # Rolling window of hold-back binding resources — what "dominant
        # hold-back reason" means for the queue-wait SLO breach Event.
        self._recent_holds: collections.deque = collections.deque(
            maxlen=recent_holds
        )
        _active = self

    # ------------------------------------------------------------------
    def record(self, rec: DecisionRecord) -> DecisionRecord:
        """Append (or collapse into) the request's ring; returns the
        stored record. Emits the Queued/Placed/Preempting Event only on a
        FRESH decision — a reconcile retry reaching the identical verdict
        bumps ``repeats`` silently, so a queued request cannot spam an
        event per backoff tick."""
        emit = False
        with self._lock:
            ring = self._objects.get(rec.request)
            if ring is None:
                ring = collections.deque(maxlen=self._per_object)
                self._objects[rec.request] = ring
                while len(self._objects) > self._max_objects:
                    self._objects.popitem(last=False)
            else:
                self._objects.move_to_end(rec.request)
            last = ring[-1] if ring else None
            if (
                last is not None
                and last.kind == rec.kind
                and last.outcome == rec.outcome
                and last.summary == rec.summary
            ):
                last.repeats += 1
                last.at = now_iso()
                # Refresh the binding/inputs digest: the shortfall the
                # operator reads should be the LATEST one observed.
                if rec.binding:
                    last.binding = rec.binding
                if rec.inputs:
                    last.inputs = rec.inputs
                if rec.flows:
                    # A re-solve reaching the identical placement mints
                    # fresh intents — keep their flow handles consumable.
                    last.flows = (last.flows + rec.flows)[-16:]
                stored = last
            else:
                self._seq += 1
                rec.seq = self._seq
                rec.decision_id = rec.decision_id or (
                    f"d-{uuid.uuid4().hex[:10]}"
                )
                rec.at = rec.at or now_iso()
                ring.append(rec)
                stored = rec
                emit = True
            stored.mono = time.monotonic()
            if rec.outcome == OUTCOME_HELD_BACK:
                self._recent_holds.append(
                    (rec.binding or {}).get("resource", "unknown")
                )
        scheduler_decisions_total.inc(kind=rec.kind, outcome=rec.outcome)
        if emit and self.recorder is not None:
            ev = self._EVENT_REASONS.get(rec.outcome)
            if ev is not None:
                try:
                    self.recorder.event(
                        _EventRef(rec.request), ev[0], ev[1], rec.summary
                    )
                except Exception:  # pragma: no cover - defensive
                    log.exception("decision event emission failed")
        return stored

    def bump_if_recent(
        self, request: str, kind: str, outcome: str,
        within_s: Optional[float] = None,
        resource: Optional[str] = None,
        exclude_resources: tuple = (),
    ) -> Optional[DecisionRecord]:
        """Collapse a repeat decision into the latest matching record
        WITHOUT the caller rebuilding its candidates/inputs: if the
        request's newest record matches (kind, outcome — and the binding
        ``resource`` when given, or anything NOT in ``exclude_resources``,
        so a capacity hold never collapses into a gate or fabric-
        reservation record and vice versa) and was recorded within
        ``within_s`` (default :attr:`hold_rescan_s`) on the monotonic
        clock, bump its repeats (feeding the hold-reason tally) and
        return it; None means the caller should build a full record (the
        binding shortfall then refreshes on record()'s own dedup)."""
        within_s = self.hold_rescan_s if within_s is None else within_s
        now = time.monotonic()
        with self._lock:
            ring = self._objects.get(request)
            last = ring[-1] if ring else None
            if (
                last is None
                or last.kind != kind
                or last.outcome != outcome
                or now - last.mono > within_s
            ):
                return None
            last_resource = (last.binding or {}).get("resource", "")
            if resource is not None and last_resource != resource:
                return None
            if last_resource in exclude_resources:
                return None
            last.repeats += 1
            last.at = now_iso()
            # Deliberately NOT advancing last.mono: the next retry past
            # the window pays one full rescan, refreshing the shortfall.
            if outcome == OUTCOME_HELD_BACK:
                self._recent_holds.append(
                    (last.binding or {}).get("resource", "unknown")
                )
        scheduler_decisions_total.inc(kind=kind, outcome=outcome)
        return last

    # ------------------------------------------------------------------
    def names(self) -> List[str]:
        with self._lock:
            return list(self._objects)

    def latest(self, name: str) -> Optional[DecisionRecord]:
        with self._lock:
            ring = self._objects.get(name)
            return ring[-1] if ring else None

    def latest_placed(self, name: str) -> Optional[DecisionRecord]:
        """Most recent successful placement decision for ``name`` (any
        placement kind) — the record an executing attach joins."""
        with self._lock:
            ring = self._objects.get(name)
            if not ring:
                return None
            for rec in reversed(ring):
                if rec.outcome == OUTCOME_PLACED:
                    return rec
        return None

    def explain(self, name: str) -> Optional[Dict[str, Any]]:
        """The /debug/scheduler/explain/<name> payload: the full ring
        oldest-first plus the latest record's summary up front."""
        with self._lock:
            ring = self._objects.get(name)
            if not ring:
                return None
            records = [r.to_doc() for r in ring]
        return {
            "request": name,
            # Which kernel produced the latest decision ("native" /
            # "python" / "legacy") — surfaced at the top so a triage of a
            # surprising placement starts from which engine layer ran it.
            "engine": records[-1].get("inputs", {}).get("engine", ""),
            "latest": records[-1],
            "decisions": records,
        }

    def link_decision(self, owner: str, nonce: str) -> str:
        """Join an attach intent to the placement decision that planned
        it: consumes one of the decision's pending Perfetto flow handles
        (drawing the decision-span → attach-span arrow) and records the
        nonce on the decision record so ``explain`` shows which intents
        executed it. Called by the resource controller at intent mint —
        through its EXPLICIT ledger handle (cmd/main wires the scheduler's
        ledger in), never the process-global: in-proc multi-replica
        harnesses construct one ledger per replica and a global would
        join intents onto whichever replica constructed last. Returns the
        decision id ("" when no placed decision for ``owner``)."""
        if not owner:
            return ""
        rec = self.latest_placed(owner)
        if rec is None:
            return ""
        with self._lock:
            if nonce and nonce not in rec.nonces:
                rec.nonces.append(nonce)
                if len(rec.nonces) > 64:  # defensive bound
                    del rec.nonces[:-64]
            flow = rec.flows.pop(0) if rec.flows else None
        if flow is not None:
            tracing.link(flow)
        return rec.decision_id

    def dominant_hold_back_reason(self) -> str:
        """Most common binding resource among recent hold-backs — what the
        queue-wait SLO breach Event names as its probable cause. Empty
        when nothing held back recently."""
        with self._lock:
            if not self._recent_holds:
                return ""
            counts = collections.Counter(self._recent_holds)
        reason, n = counts.most_common(1)[0]
        return f"{reason} ({n}/{sum(counts.values())} recent hold-backs)"

    # ------------------------------------------------------------------
    def snapshot(self) -> Dict[str, Any]:
        """Whole-ledger view (the crash dump / debug index payload)."""
        with self._lock:
            objects = {
                name: [r.to_doc() for r in ring]
                for name, ring in self._objects.items()
            }
            holds = list(self._recent_holds)
        return {
            "requests": objects,
            "recent_hold_back_reasons": holds,
            "dominant_hold_back": self.dominant_hold_back_reason(),
        }

    def dump(self, path: str) -> Optional[str]:
        """Write the ledger to ``path``. Never raises — runs on crash
        paths beside the flight/profile/SLO black boxes."""
        try:
            doc = {"written_at": now_iso(), "pid": os.getpid()}
            doc.update(self.snapshot())
            with open(path, "w") as f:
                json.dump(doc, f, indent=1)
        except (OSError, ValueError, TypeError):
            log.warning("decision ledger dump to %s failed", path)
            return None
        return path

    def reset(self) -> None:
        with self._lock:
            self._objects.clear()
            self._recent_holds.clear()


# ----------------------------------------------------------------------
def active() -> Optional[DecisionLedger]:
    return _active


def deactivate(ledger: Optional[DecisionLedger] = None) -> None:
    """Drop the module-global active ledger (test isolation; a specific
    ``ledger`` only deactivates if it is still the active one)."""
    global _active
    if ledger is None or _active is ledger:
        _active = None


def dump_file(path: Optional[str] = None) -> Optional[str]:
    """Write the active ledger to ``path`` (default $TPUC_DECISIONS_FILE)
    — the crash/soak failure artifact beside the flight, profile, SLO and
    fleet black boxes. Never raises."""
    path = path or os.environ.get("TPUC_DECISIONS_FILE")
    led = _active
    if not path or led is None:
        return None
    return led.dump(path)
