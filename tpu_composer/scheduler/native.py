"""ctypes binding to the native placement kernel (native/tpusched.cc).

Loads ``libtpusched.so`` from (in order) $TPUSCHED_LIB, the repo's
``native/build`` directory, or the system loader. Returns None when
absent so the engine falls back to the pure-Python kernel in
scheduler/snapshot.py with bit-identical decisions (differential-fuzzed
in tests/test_native_sched.py) — the library is an optimization for the
O(cluster) scans under the allocation lock, not a requirement.

``TPUC_NATIVE_SCHED=0`` disables the whole native-scheduler layer
(snapshot AND kernel); the scheduler then runs the legacy store-walk
engine unchanged.
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import List, Optional, Tuple

_lock = threading.Lock()
_loaded = False
_lib: Optional["_NativeLib"] = None

_I32P = ctypes.POINTER(ctypes.c_int32)
_I64P = ctypes.POINTER(ctypes.c_int64)
_U8P = ctypes.POINTER(ctypes.c_uint8)


def native_sched_enabled() -> bool:
    """The master switch for the snapshot + native-kernel layer."""
    return os.environ.get("TPUC_NATIVE_SCHED", "1") != "0"


class _NativeLib:
    def __init__(self, cdll: ctypes.CDLL) -> None:
        self._c = cdll
        self._c.tpus_version.restype = ctypes.c_int
        self._c.tpus_scan.restype = ctypes.c_int32
        self._c.tpus_scan.argtypes = [
            ctypes.c_int32,
            _I32P, _I32P, _I32P, _U8P,
            _I64P, _I64P, _I64P, _I64P,
            ctypes.c_int32,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32,
            _I32P, _I32P, _I32P, _I32P,
        ]
        self._c.tpus_victims.restype = ctypes.c_int32
        self._c.tpus_victims.argtypes = [
            ctypes.c_int32,
            _I32P, _I32P, _U8P,
            _I64P, _I64P, _I64P, _I64P,
            ctypes.c_int32,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32,
            _I64P, _I64P, _I32P,
            _I32P, _I32P, _I32P,
            ctypes.c_int32, ctypes.c_int32,
            _I32P, _I64P,
        ]

    def version(self) -> int:
        return int(self._c.tpus_version())

    def scan(
        self, n, slots, used, hidx, flags, cpu, mem, eph, pods,
        other, chips: int, count: int,
    ):
        """Mirror of snapshot.py's py_scan over the same packed arrays:
        returns (num_ok, out_free, out_verdict, out_order, sel) with
        sel=None when no selection was requested or fewer than ``count``
        nodes fit. Raises OSError on a kernel-reported argument error so
        the caller can fall back to the Python path."""
        out_free = (ctypes.c_int32 * n)()
        out_verdict = (ctypes.c_int32 * n)()
        out_order = (ctypes.c_int32 * n)()
        out_sel = (ctypes.c_int32 * max(1, count))()
        num_ok = self._c.tpus_scan(
            n, slots, used, hidx, flags, cpu, mem, eph, pods,
            1 if other is not None else 0,
            other.milli_cpu if other is not None else 0,
            other.memory if other is not None else 0,
            other.ephemeral_storage if other is not None else 0,
            other.allowed_pod_number if other is not None else 0,
            chips, count,
            out_free, out_verdict, out_order, out_sel,
        )
        if num_ok < 0:
            raise OSError("tpus_scan rejected its arguments")
        sel = None
        if count >= 1 and num_ok >= count:
            sel = [out_sel[i] for i in range(count)]
        return num_ok, out_free, out_verdict, out_order, sel

    def victims(
        self, n, slots, used, usable, cpu, mem, eph, pods,
        other, chips: int, num_hosts: int,
        target_mode: int, target_idx: int,
        cand_prio, cand_chips, cand_rank,
        freed_off, freed_idx, freed_amt,
        max_exh_cands: int, max_exh_size: int,
    ) -> Tuple[List[int], dict]:
        """Returns (victim candidate indices, last_search-shaped info).
        Raises OSError on a kernel-reported argument error."""
        ncand = len(cand_rank)
        out_sel = (ctypes.c_int32 * max(1, ncand))()
        out_info = (ctypes.c_int64 * 4)()
        nv = self._c.tpus_victims(
            n, slots, used, usable, cpu, mem, eph, pods,
            1 if other is not None else 0,
            other.milli_cpu if other is not None else 0,
            other.memory if other is not None else 0,
            other.ephemeral_storage if other is not None else 0,
            other.allowed_pod_number if other is not None else 0,
            chips, num_hosts, target_mode, target_idx,
            ncand, cand_prio, cand_chips, cand_rank,
            freed_off, freed_idx, freed_amt,
            max_exh_cands, max_exh_size,
            out_sel, out_info,
        )
        if nv < 0:
            raise OSError("tpus_victims rejected its arguments")
        mode = int(out_info[0])
        if mode == 1:
            info = {
                "mode": "exhaustive",
                "candidates": ncand,
                "set_size": int(out_info[1]),
                "victim_priority_sum": int(out_info[2]),
                "victim_chips": int(out_info[3]),
            }
        elif mode == 2:
            info = {
                "mode": "greedy+prune",
                "candidates": ncand,
                "set_size": int(out_info[1]),
            }
        else:
            info = {"mode": "infeasible", "candidates": ncand}
        return [out_sel[i] for i in range(nv)], info


def _candidate_paths() -> List[str]:
    paths = []
    env = os.environ.get("TPUSCHED_LIB")
    if env:
        paths.append(env)
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    paths.append(os.path.join(here, "native", "build", "libtpusched.so"))
    paths.append("libtpusched.so")
    return paths


def native_lib() -> Optional[_NativeLib]:
    """Load (once) and return the native library, or None. The
    TPUC_NATIVE_SCHED=0 kill switch is enforced by the caller
    (ClusterScheduler) — the load result is cached process-wide and must
    not capture a transient env state."""
    global _loaded, _lib
    with _lock:
        if _loaded:
            return _lib
        _loaded = True
        for path in _candidate_paths():
            try:
                _lib = _NativeLib(ctypes.CDLL(path))
                return _lib
            except (OSError, AttributeError):
                continue
        return None
