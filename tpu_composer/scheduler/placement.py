"""Placement engine — cluster-wide capacity accounting and host selection.

This is the allocator's placement brain, lifted out of
``ComposabilityRequestReconciler`` (which used to keep ``_pick_nodes`` /
``_pick_extra_nodes`` / ``_used_slots_map`` inline) so that placement policy
is arbitrated cluster-wide instead of per-request: the scheduler facade
(``scheduler/core.py``) runs priority, gang-admission and preemption
decisions on top of the primitives here, and the controller only executes
what the engine decides — the composable split arXiv:2506.23628 argues for
(placement engine separate from the reconciler that executes it).

Two placement properties matter for TPU slices and drive the scoring:

- **Fragmentation-aware bin-packing** (tightest-fit): sub-host chip groups
  pack onto already-fragmented hosts, keeping whole hosts intact for the
  topology shapes that need all their ports. The 256-node mixed-size storm
  exposed the opposite (least-loaded-first) policy deadlocking whole-host
  slices behind scattered singles — fragmentation the reference operator
  never sees because its devices are independent, while TPU workers are
  all-or-nothing port groups. Selecting the ``count`` hosts with the least
  free-after-placement is sum-optimal for this objective.
- **ICI contiguity**: multi-host slices want physically adjacent hosts on
  the optical fabric (wrap-around links span neighboring trays; compare
  arXiv:2404.06467's fabric-topology-aware assignment). Host adjacency is
  inferred from the trailing integer in the node name (worker-3, tpu-host-12);
  among equally-packed host sets the engine prefers the window with the
  smallest index span.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tpu_composer.api.types import (
    ComposabilityRequest,
    ComposableResource,
    LABEL_MANAGED_BY,
    Node,
)
from tpu_composer.fabric.provider import FabricError
from tpu_composer.scheduler import snapshot as snap_mod
from tpu_composer.topology.slices import SliceShape


class AllocationError(FabricError):
    """No valid placement exists right now — surfaced in status.error."""


_TRAILING_INT = re.compile(r"(\d+)$")


def host_index(name: str) -> Optional[int]:
    """Fabric position inferred from the node name's trailing integer
    (worker-3 -> 3); None when the name carries no index."""
    m = _TRAILING_INT.search(name)
    return int(m.group(1)) if m else None


class PlacementEngine:
    """Capacity accounting + fragmentation/contiguity-scored host picking.

    Stateless aside from the store handle: every decision re-reads the
    cluster, so the caller (the request controller under its allocation
    lock, or the defrag planner) always sees placeholders written by the
    allocation that just finished.

    The handle is normally the CachedClient (cmd/main ``--cached-reads``),
    which is what makes "re-read everything per decision" affordable at
    fleet scale: capacity_maps' two full scans and every feasibility
    probe's node list are informer-cache snapshots (zero RTT), and the
    write-response folding in the client preserves the
    placeholders-visible-under-the-lock invariant the docstring above
    relies on.

    With a :class:`~tpu_composer.scheduler.snapshot.ChipIndexSnapshot`
    attached (ClusterScheduler wires one unless TPUC_NATIVE_SCHED=0), the
    capacity views come from incrementally-maintained accounting instead
    of store walks, and the fit search / candidate-verdict scan run over
    the snapshot's packed arrays — through the native kernel
    (native/tpusched.cc) when loaded, else the bit-identical pure-Python
    port. One scan serves both the host selection and the decision
    ledger's candidate doc (the retained-scan reuse in
    candidate_verdicts), which is what brought the decision-plane
    overhead back under the perf-smoke gate.
    """

    def __init__(self, store, snapshot=None, native=None) -> None:
        self.store = store
        #: ChipIndexSnapshot or None (legacy store-walk engine).
        self.snapshot = snapshot
        #: scheduler.native._NativeLib or None (pure-Python kernel).
        self.native = native
        # The last packed scan (fit search or verdict scan) and its
        # identity key — candidate_verdicts reuses it when the decision
        # inputs are unchanged instead of re-scanning the cluster.
        self._last_scan: Optional[tuple] = None
        #: "native" | "python" | "legacy" — which kernel produced the last
        #: selection (observability: cmd/main logs it, bench records it).
        self.last_scan_kind = "legacy"

    def _snap(self):
        s = self.snapshot
        return s if s is not None and s.active else None

    @property
    def kernel_kind(self) -> str:
        """Which engine layer decisions run on: "native" (packed snapshot
        + C kernel), "python" (packed snapshot, pure-Python kernel), or
        "legacy" (per-decision store walks)."""
        if self._snap() is None:
            return "legacy"
        return "native" if self.native is not None else "python"

    # ------------------------------------------------------------------
    # capacity accounting
    # ------------------------------------------------------------------
    def capacity_maps(
        self, exclude_request: str = ""
    ) -> Tuple[Dict[str, int], Dict[str, int]]:
        """ONE store pass building the two views a placement decision
        needs, node -> chips claimed there:

        - ``occupied``: every live claim — all instantiated children plus
          OTHER requests' placeholder rows (rows whose child doesn't exist
          yet; without the placeholder term, concurrent allocations all
          pick the same least-loaded node before any child materializes —
          the occupancy check vs other requests,
          composabilityrequest_controller.go:386-443). The excluded
          request's own placeholders are omitted because its re-solve
          replaces them, but its own CHILDREN count: the backfill gate
          must see capacity a grow-path request already holds, or growing
          onto a contended host reads as free and the gate lets a
          low-priority grow starve a pending high-priority demand.
        - ``without``: additionally omits the excluded request's own
          children — the view its OWN host picking must use (its survivors
          don't compete with their replacement).

        Allocation holds the controller's lock, so per-candidate rescans
        would serialize the whole fleet behind O(N*R) work — hence both
        maps from one pass."""
        snap = self._snap()
        if snap is not None:
            snap.sync()
            return snap.capacity_views(exclude_request)
        occupied: Dict[str, int] = {}
        without: Dict[str, int] = {}
        existing = {c.name: c for c in self.store.list(ComposableResource)}
        for c in existing.values():
            if c.being_deleted:
                continue
            n = c.spec.chip_count if c.spec.type == "tpu" else 1
            node = c.spec.target_node
            occupied[node] = occupied.get(node, 0) + n
            if c.metadata.labels.get(LABEL_MANAGED_BY) != exclude_request:
                without[node] = without.get(node, 0) + n
        for other in self.store.list(ComposabilityRequest):
            if other.name == exclude_request or other.being_deleted:
                continue
            per_member = (
                other.status.slice.chips_per_host
                if other.spec.resource.type == "tpu"
                and other.status.slice.chips_per_host
                else 1
            )
            for name, rs in other.status.resources.items():
                if name not in existing and rs.node_name:
                    occupied[rs.node_name] = (
                        occupied.get(rs.node_name, 0) + per_member
                    )
                    without[rs.node_name] = (
                        without.get(rs.node_name, 0) + per_member
                    )
        return occupied, without

    def used_slots_map(self, exclude_request: str = "") -> Dict[str, int]:
        """The placement view only (see capacity_maps)."""
        return self.capacity_maps(exclude_request)[1]

    def node_fits(
        self,
        req: ComposabilityRequest,
        node: Node,
        chips: int,
        used: Dict[str, int],
    ) -> bool:
        if node.status.tpu_slots - used.get(node.metadata.name, 0) < chips:
            return False
        other = req.spec.resource.other_spec
        if other is not None:
            # CheckNodeCapacitySufficient analog (utils/nodes.go:78-117).
            if (
                node.status.milli_cpu < other.milli_cpu
                or node.status.memory < other.memory
                or node.status.ephemeral_storage < other.ephemeral_storage
                or node.status.allowed_pod_number < other.allowed_pod_number
            ):
                return False
        return True

    def fragmentation(
        self,
        quarantined: Set[str] = frozenset(),
        used: Optional[Dict[str, int]] = None,
    ) -> float:
        """Share of free TPU capacity stranded on partially-used hosts:
        ``1 - (free slots on fully-free hosts / total free slots)`` over
        schedulable hosts. 0.0 means every free port sits on an empty host
        (any multi-host shape that fits the totals can compose); 1.0 means
        all free capacity hides in gaps no whole-host worker can use.
        0.0 when nothing is free (an exactly-full cluster isn't fragmented,
        it's full)."""
        used = self.used_slots_map() if used is None else used
        total_free = 0
        whole_free = 0
        for n in self.store.list(Node):
            if (
                not n.status.ready
                or n.spec.unschedulable
                or n.metadata.name in quarantined
            ):
                continue
            u = used.get(n.metadata.name, 0)
            free = max(0, n.status.tpu_slots - u)
            total_free += free
            if u == 0:
                whole_free += free
        if total_free == 0:
            return 0.0
        return 1.0 - whole_free / total_free

    # ------------------------------------------------------------------
    # host selection
    # ------------------------------------------------------------------
    def pick_hosts(
        self,
        req: ComposabilityRequest,
        shape: SliceShape,
        quarantined: Set[str],
        used: Optional[Dict[str, int]] = None,
    ) -> List[str]:
        """Choose shape.num_hosts nodes with free TPU ports + capacity.
        `quarantined` is the allocation pass's one DeviceTaintRule scan,
        threaded through so no picker re-lists.

        Policies (:361-467 analog): explicit target_node (single-host only),
        samenode (single-host auto-pick), differentnode/topology (spread).
        """
        res = req.spec.resource
        if used is None:
            used = self.used_slots_map(req.name)
        if res.target_node:
            if shape.num_hosts > 1:
                raise AllocationError(
                    f"topology {shape.topology} spans {shape.num_hosts} hosts;"
                    " target_node only supports single-host slices"
                )
            node = self.store.try_get(Node, res.target_node)
            if node is None:
                raise AllocationError(
                    f"target node {res.target_node} does not exist"
                )
            if res.target_node in quarantined:
                raise AllocationError(
                    f"target node {res.target_node} is quarantined"
                    " (fabric attach budget exhausted)"
                )
            if not self.node_fits(req, node, shape.chips_per_host, used):
                raise AllocationError(
                    f"target node {res.target_node} lacks capacity for"
                    f" {shape.chips_per_host} chips"
                )
            return [res.target_node]

        # For tpu, allocation_policy does not constrain host count — the
        # topology dictates it (a 2x2x2 slice needs exactly 2 hosts). The
        # policy is honored as a placement preference: tightest-fit packing
        # (see pick_slice_hosts); differentnode is identical for slices
        # since workers always land on distinct hosts.
        return self.pick_slice_hosts(
            req, shape, exclude=set(), count=shape.num_hosts,
            quarantined=quarantined, used=used,
        )

    def pick_slice_hosts(
        self,
        req: ComposabilityRequest,
        shape: SliceShape,
        exclude: Set[str],
        count: int,
        quarantined: Set[str],
        used: Optional[Dict[str, int]] = None,
    ) -> List[str]:
        """Slice placement: `count` hosts with capacity for one worker's
        chip group each. Fresh allocations pass exclude=∅ and the full host
        count; the grow path excludes surviving members' hosts and asks for
        only the delta — one filter/sort, so placement policy can't diverge
        between the two."""
        if used is None:
            used = self.used_slots_map(req.name)
        if count < 1:
            return []
        snap = self._snap()
        if snap is not None:
            num_ok, _free, _verd, _order, sel = self._kernel_scan(
                req, shape.chips_per_host, quarantined, exclude, used,
                count, snap,
            )
            if sel is None:
                raise AllocationError(
                    f"need {count} {'more ' if exclude else ''}hosts with"
                    f" {shape.chips_per_host} free TPU ports for"
                    f" {shape.topology}, only {num_ok} available"
                )
            names = snap.names
            return [names[i] for i in sel]
        candidates = [
            n for n in self.store.list(Node)
            if n.metadata.name not in exclude
            and n.metadata.name not in quarantined
            and n.status.ready and not n.spec.unschedulable
            and self.node_fits(req, n, shape.chips_per_host, used)
        ]
        if len(candidates) < count:
            raise AllocationError(
                f"need {count} {'more ' if exclude else ''}hosts with"
                f" {shape.chips_per_host} free TPU ports for"
                f" {shape.topology}, only {len(candidates)} available"
            )

        def free_after(n: Node) -> int:
            return n.status.tpu_slots - used.get(n.metadata.name, 0)

        # Tightest-fit first (fewest ports left free after placement) —
        # picking the `count` smallest leftovers is sum-optimal for the
        # fragmentation objective, so every refinement below must tie it.
        candidates.sort(key=lambda n: (free_after(n), n.metadata.name))
        greedy = candidates[:count]
        if count <= 1:
            return [n.metadata.name for n in greedy]
        best_sum = sum(free_after(n) for n in greedy)

        # ICI-contiguity refinement: among host sets that tie the packing
        # optimum, prefer the window of consecutive fabric indices with the
        # smallest span (0 = perfectly contiguous trays). Hosts without a
        # parseable index can't participate in a window.
        indexed = [
            (host_index(n.metadata.name), n)
            for n in candidates
            if host_index(n.metadata.name) is not None
        ]
        indexed.sort(key=lambda t: (t[0], t[1].metadata.name))
        best_window = None  # (span, start_index, [nodes])
        for i in range(len(indexed) - count + 1):
            window = indexed[i : i + count]
            if any(
                window[j][0] == window[j + 1][0] for j in range(count - 1)
            ):
                # Duplicate trailing integers (rack-a-host2 / rack-b-host2)
                # are NOT adjacency — a duplicate both skews the span
                # negative and can mask a real gap ([2,2,4] spans 0).
                continue
            if sum(free_after(n) for _, n in window) != best_sum:
                continue
            span = window[-1][0] - window[0][0] - (count - 1)
            key = (span, window[0][0])
            if best_window is None or key < best_window[:2]:
                best_window = (span, window[0][0], [n for _, n in window])
        if best_window is not None:
            return [n.metadata.name for n in best_window[2]]
        return [n.metadata.name for n in greedy]

    def pick_scalar_nodes(
        self,
        req: ComposabilityRequest,
        count: int,
        existing: Sequence[str],
        quarantined: Set[str],
        used: Optional[Dict[str, int]] = None,
    ) -> List[str]:
        """gpu/cxlmemory placement — the reference's independent-device
        policies (samenode / differentnode, :361-467) on top of the same
        capacity map the slice picker uses."""
        res = req.spec.resource
        if used is None:
            used = self.used_slots_map(req.name)
        if res.target_node:
            node = self.store.try_get(Node, res.target_node)
            if node is None:
                raise AllocationError(
                    f"target node {res.target_node} does not exist"
                )
            if res.target_node in quarantined:
                raise AllocationError(
                    f"target node {res.target_node} is quarantined"
                    " (fabric attach budget exhausted)"
                )
            # Capacity must cover everything this request puts there.
            already = sum(1 for e in existing if e == res.target_node)
            if not self.node_fits(req, node, already + count, used):
                raise AllocationError(
                    f"target node {res.target_node} lacks"
                    f" {already + count} free device ports"
                )
            return [res.target_node] * count
        nodes = [
            n for n in self.store.list(Node)
            if n.status.ready and not n.spec.unschedulable
            and n.metadata.name not in quarantined
            and self.node_fits(req, n, 1, used)
        ]
        if not nodes:
            raise AllocationError("no schedulable node with free device ports")
        if res.allocation_policy == "samenode":
            if existing:
                anchor_name = existing[0]
            else:
                anchor_name = min(
                    nodes, key=lambda n: (used.get(n.name, 0), n.name)
                ).metadata.name
            anchor = self.store.try_get(Node, anchor_name)
            already = sum(1 for e in existing if e == anchor_name)
            if anchor is None or not self.node_fits(
                req, anchor, already + count, used
            ):
                raise AllocationError(
                    f"samenode anchor {anchor_name} lacks"
                    f" {already + count} free device ports"
                )
            return [anchor_name] * count
        # differentnode: spread over distinct nodes not already used (:444-467)
        taken = set(existing)
        fresh = [n.metadata.name for n in nodes if n.metadata.name not in taken]
        if len(fresh) < count:
            raise AllocationError(
                f"differentnode policy needs {count} unused nodes,"
                f" found {len(fresh)}"
            )
        fresh.sort(key=lambda nm: (used.get(nm, 0), nm))
        return fresh[:count]

    # ------------------------------------------------------------------
    # packed-array kernel dispatch (snapshot attached): native scan when
    # the library is loaded, bit-identical pure-Python port otherwise
    # ------------------------------------------------------------------
    def _scan_inputs_key(self, chips, quarantined, exclude, used, other, snap):
        """Identity of one scan's inputs. ``used`` rides by object id:
        within one snapshot version the capacity views for a given exclude
        set are deterministic, and exclude/quarantine are in the key, so
        an id collision across decisions can only alias an identical
        scan."""
        okey = None if other is None else (
            other.milli_cpu, other.memory,
            other.ephemeral_storage, other.allowed_pod_number,
        )
        return (
            chips, tuple(sorted(quarantined)), tuple(sorted(exclude)),
            id(used), snap.version, okey,
        )

    def _kernel_scan(self, req, chips, quarantined, exclude, used, count, snap):
        """One pass over the packed snapshot: per-node free + verdict
        codes, the candidate ordering, and (count >= 1) the selected host
        indices. The scan is retained so candidate_verdicts for the same
        decision reuses it instead of walking the cluster again."""
        snap.ensure_dense()
        n = len(snap.names)
        used_arr = snap.pack_used(used)
        flags = snap.pack_flags(quarantined, exclude)
        other = req.spec.resource.other_spec
        res = None
        if self.native is not None:
            try:
                res = self.native.scan(
                    n, snap._slots, used_arr, snap._hidx, flags,
                    snap._cpu, snap._mem, snap._eph, snap._pods,
                    other, chips, count,
                )
                self.last_scan_kind = "native"
            except OSError:
                res = None
        if res is None:
            res = snap_mod.py_scan(
                n, snap._slots, used_arr, snap._hidx, flags,
                snap._cpu, snap._mem, snap._eph, snap._pods,
                other, chips, count,
            )
            self.last_scan_kind = "python"
        key = self._scan_inputs_key(chips, quarantined, exclude, used,
                                    other, snap)
        self._last_scan = (key, list(snap.names), res)
        return res

    def _scan_candidates(self, names, res, chips, cap=None):
        """Materialize the candidates-considered doc from a retained scan
        — only the first ``cap`` dicts when the ledger will truncate
        anyway (the O(nodes)-dicts materialization was half the decision-
        plane regression BENCH_r10 measured)."""
        _num_ok, free, verd, order, _sel = res
        total = len(order) if cap is None else min(cap, len(order))
        out: List[Dict[str, object]] = []
        for k in range(total):
            i = order[k]
            v = verd[i]
            if v == snap_mod.V_NO_PORTS:
                vs = f"no-tpu-ports free={free[i]} need={chips}"
            else:
                vs = snap_mod.VERDICT_STR[v]
            out.append({
                "node": names[i], "free": int(free[i]), "verdict": vs,
            })
        return out

    # ------------------------------------------------------------------
    # decision-ledger explain helpers (never on the hot path: built only
    # when the scheduler's DecisionLedger is enabled)
    # ------------------------------------------------------------------
    def node_verdict(
        self,
        req: ComposabilityRequest,
        node: Node,
        chips: int,
        used: Dict[str, int],
        quarantined: Set[str],
        exclude: Set[str] = frozenset(),
    ) -> Optional[str]:
        """Why this node cannot host ``chips`` for ``req`` (None = it
        can). The explain twin of :meth:`node_fits`, split so each
        rejection names its constraint instead of collapsing to bool."""
        name = node.metadata.name
        if name in exclude:
            return "excluded"
        if name in quarantined:
            return "quarantined"
        if not node.status.ready:
            return "not-ready"
        if node.spec.unschedulable:
            return "cordoned"
        free = node.status.tpu_slots - used.get(name, 0)
        if free < chips:
            return f"no-tpu-ports free={max(0, free)} need={chips}"
        other = req.spec.resource.other_spec
        if other is not None and (
            node.status.milli_cpu < other.milli_cpu
            or node.status.memory < other.memory
            or node.status.ephemeral_storage < other.ephemeral_storage
            or node.status.allowed_pod_number < other.allowed_pod_number
        ):
            return "node-resources"
        return None

    def candidate_verdicts(
        self,
        req: ComposabilityRequest,
        chips: int,
        quarantined: Set[str],
        used: Dict[str, int],
        exclude: Set[str] = frozenset(),
        cap: Optional[int] = None,
    ) -> List[Dict[str, object]]:
        """Every node's verdict for one worker's chip group — the
        candidates-considered section of a DecisionRecord. Sorted fitting
        nodes first (tightest-fit order, mirroring the picker), then
        rejected ones by name. ``cap`` truncates AFTER the sort (what the
        ledger's candidate cap would keep anyway). With a snapshot
        attached, the verdicts come from the same packed scan the
        placement already ran when the inputs match — the second full
        walk BENCH_r10 charged to the decision plane is gone."""
        snap = self._snap()
        if snap is not None:
            other = req.spec.resource.other_spec
            key = self._scan_inputs_key(chips, quarantined, exclude, used,
                                        other, snap)
            if self._last_scan is not None and self._last_scan[0] == key:
                _key, names, res = self._last_scan
            else:
                res = self._kernel_scan(
                    req, chips, quarantined, exclude, used, 0, snap
                )
                names = self._last_scan[1]
            return self._scan_candidates(names, res, chips, cap=cap)
        out: List[Dict[str, object]] = []
        for n in self.store.list(Node):
            verdict = self.node_verdict(req, n, chips, used, quarantined,
                                        exclude=exclude)
            out.append({
                "node": n.metadata.name,
                "free": max(0, n.status.tpu_slots
                            - used.get(n.metadata.name, 0)),
                "verdict": verdict or "ok",
            })
        out.sort(key=lambda c: (
            c["verdict"] != "ok", c["free"] if c["verdict"] == "ok" else 0,
            c["node"],
        ))
        return out if cap is None else out[:cap]

    def tiebreak_rationale(
        self, chosen: Sequence[str], used: Dict[str, int]
    ) -> str:
        """Reconstruct why THESE hosts won from the same inputs the picker
        scored: the tightest-fit leftover sum, and the ICI window span when
        every chosen host carries a parseable fabric index. Read-only over
        the decision's own ``used`` map — the hot picker stays untouched."""
        if not chosen:
            return ""
        frees = []
        for name in chosen:
            node = self.store.try_get(Node, name)
            if node is None:
                return "tightest-fit"
            frees.append(node.status.tpu_slots - used.get(name, 0))
        parts = [f"tightest-fit leftover={sum(frees)}"]
        if len(chosen) > 1:
            idx = [host_index(n) for n in chosen]
            if all(i is not None for i in idx):
                span = max(idx) - min(idx) - (len(chosen) - 1)  # type: ignore[arg-type]
                parts.append(
                    "ICI-contiguous window" if span == 0
                    else f"ICI window span={span}"
                )
        return ", ".join(parts)

    # ------------------------------------------------------------------
    # feasibility probes (gate + preemption simulation)
    # ------------------------------------------------------------------
    def schedulable_nodes(self, quarantined: Set[str]) -> List[Node]:
        """One snapshot of the hosts placement may use — callers that run
        many feasibility probes (the gate, the victim-set search, defrag's
        hold-back check) take this ONCE per pass and thread it through,
        instead of re-listing the Node collection per probe under the
        allocation lock."""
        return [
            n for n in self.store.list(Node)
            if n.status.ready
            and not n.spec.unschedulable
            and n.metadata.name not in quarantined
        ]

    def demand_feasible(
        self,
        req: ComposabilityRequest,
        num_hosts: int,
        chips_per_host: int,
        quarantined: Set[str],
        used: Dict[str, int],
        anchor: str = "",
        nodes: Optional[List[Node]] = None,
        exclude_nodes: tuple = (),
    ) -> bool:
        """Could a (num_hosts × chips_per_host) demand place under `used`?
        Pure counting — no selection — so gate and victim-set search can
        simulate many capacity states cheaply. ``anchor`` pins the demand
        to one specific host beyond what the spec says — a samenode
        request with devices already placed can only ever grow on its
        anchor node, and a gate probe that ignored that would call an
        actually-starved request 'still feasible' elsewhere. ``nodes`` is
        an optional schedulable_nodes() snapshot to probe against."""
        res = req.spec.resource
        pinned = anchor or res.target_node
        if pinned:
            node = None
            if nodes is not None:
                node = next(
                    (n for n in nodes if n.metadata.name == pinned), None
                )
            if node is None:
                # target_node placement bypasses cordon in the picker, so
                # the probe falls back to a direct lookup rather than
                # calling a pinned demand infeasible on a cordoned host.
                node = self.store.try_get(Node, pinned)
            return (
                node is not None
                and pinned not in quarantined
                and num_hosts == 1
                and self.node_fits(req, node, chips_per_host, used)
            )
        if nodes is None:
            nodes = self.schedulable_nodes(quarantined)
        fitting = sum(
            1 for n in nodes
            if n.metadata.name not in exclude_nodes
            and self.node_fits(req, n, chips_per_host, used)
        )
        return fitting >= num_hosts
