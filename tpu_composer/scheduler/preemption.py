"""Victim-set computation: who must go so a higher-priority slice can fit.

When the placement engine reports no valid host set for a request, the
preemptor searches for a **minimal** set of strictly-lower-priority
requests whose eviction would make the placement feasible. Minimality is
cardinality-first (fewest workloads disturbed), then least total victim
priority, then least capacity evicted — so a single 4-chip victim beats two
2-chip ones, and among equals the cheaper/younger victims go first.

Respected constraints:

- only strictly-lower-priority requests are candidates, and only when the
  preemptor's own ``preemptionPolicy`` is ``PreemptLowerPriority``;
- a victim with ``preemptionPolicy: Never`` is untouchable;
- capacity freed on quarantined / cordoned / gone nodes counts for nothing
  (the placement engine will not use it), so requests living there are
  never chosen — evicting them would disturb a workload without helping
  the preemptor (the quarantine-aware half of the priority-inversion
  guard).

The preemptor only *computes* the set. Execution — deleting the victims'
children so their own state machines re-queue them — stays in the request
controller, through the same delete/re-solve paths every other disruption
uses.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from tpu_composer.api.types import (
    ComposabilityRequest,
    ComposableResource,
    LABEL_MANAGED_BY,
    Node,
    PREEMPT_LOWER_PRIORITY,
    PREEMPT_NEVER,
)
from tpu_composer.topology.slices import SliceShape

#: Exhaustive minimal-set search bound: above this many candidate victims
#: (or when no set ≤ _EXHAUSTIVE_MAX_SIZE works) fall back to greedy+prune,
#: which yields an irreducible (if not always minimum-cardinality) set.
_EXHAUSTIVE_MAX_CANDIDATES = 12
_EXHAUSTIVE_MAX_SIZE = 6


@dataclass
class _Candidate:
    name: str
    priority: int
    freed: Dict[str, int]  # node -> chips usable capacity eviction frees
    total_chips: int
    creation: str


class Preemptor:
    def __init__(self, store, engine) -> None:
        self.store = store
        self.engine = engine
        # Minimality rationale for the LAST compute_victims call — the
        # decision ledger reads it right after the call returns. Safe as
        # instance state because every caller runs under the scheduler's
        # allocation lock (core.place is the only production call site).
        self.last_search: dict = {}

    # ------------------------------------------------------------------
    def compute_victims(
        self,
        req: ComposabilityRequest,
        shape: SliceShape,
        quarantined: Set[str],
        used: Dict[str, int],
    ) -> List[str]:
        """Minimal victim set making `req`'s shape placeable, or [] when
        preemption is disallowed or cannot help."""
        self.last_search = {}
        if req.spec.preemption_policy != PREEMPT_LOWER_PRIORITY:
            self.last_search = {"mode": "disallowed"}
            return []
        candidates = self._candidates(req, quarantined)
        if not candidates:
            self.last_search = {"mode": "no-candidates", "candidates": 0}
            return []

        # ONE node snapshot for every feasibility probe: the exhaustive
        # search runs up to ~2.5k subset probes, and each demand_feasible
        # would otherwise re-list the whole Node collection — on a wire
        # store that is thousands of scans per failed placement, held
        # under the allocation lock. node_fits is pure given the node and
        # a used map, so the snapshot is exact.
        usable_nodes = self.engine.schedulable_nodes(quarantined)
        target = req.spec.resource.target_node
        target_node = next(
            (n for n in usable_nodes if n.metadata.name == target), None
        )

        def feasible(combo: Tuple[_Candidate, ...]) -> bool:
            sim = dict(used)
            for c in combo:
                for node, chips in c.freed.items():
                    sim[node] = max(0, sim.get(node, 0) - chips)
            if target:
                return (
                    target_node is not None
                    and shape.num_hosts == 1
                    and self.engine.node_fits(
                        req, target_node, shape.chips_per_host, sim
                    )
                )
            fitting = sum(
                1
                for n in usable_nodes
                if self.engine.node_fits(req, n, shape.chips_per_host, sim)
            )
            return fitting >= shape.num_hosts

        # Deterministic candidate order: cheapest victims first.
        candidates.sort(
            key=lambda c: (c.priority, c.total_chips, c.creation, c.name)
        )

        # Native kernel (native/tpusched.cc tpus_victims) when the engine
        # carries a packed snapshot and the library loaded: the same
        # exhaustive-then-greedy search over the packed arrays, probes in
        # O(freed entries) instead of O(nodes). None = fall back to the
        # Python search below (bit-identical by the differential fuzz).
        native = self._native_search(req, shape, quarantined, used, candidates)
        if native is not None:
            victims, self.last_search = native
            return victims

        if not feasible(tuple(candidates)):
            self.last_search = {
                "mode": "infeasible", "candidates": len(candidates),
            }
            return []  # even evicting everyone eligible wouldn't fit

        if len(candidates) <= _EXHAUSTIVE_MAX_CANDIDATES:
            for size in range(1, min(len(candidates), _EXHAUSTIVE_MAX_SIZE) + 1):
                best: Optional[Tuple[tuple, Tuple[_Candidate, ...]]] = None
                for combo in itertools.combinations(candidates, size):
                    if not feasible(combo):
                        continue
                    key = (
                        sum(c.priority for c in combo),
                        sum(c.total_chips for c in combo),
                        tuple(c.name for c in combo),
                    )
                    if best is None or key < best[0]:
                        best = (key, combo)
                if best is not None:
                    self.last_search = {
                        "mode": "exhaustive",
                        "candidates": len(candidates),
                        "set_size": size,
                        "victim_priority_sum": best[0][0],
                        "victim_chips": best[0][1],
                    }
                    return [c.name for c in best[1]]

        victims = self._greedy_prune(candidates, feasible)
        self.last_search = {
            "mode": "greedy+prune",
            "candidates": len(candidates),
            "set_size": len(victims),
        }
        return victims

    # ------------------------------------------------------------------
    def _native_search(
        self,
        req: ComposabilityRequest,
        shape: SliceShape,
        quarantined: Set[str],
        used: Dict[str, int],
        candidates: List[_Candidate],
    ):
        """Pack the sorted candidates + capacity state and run the victim
        search in the native kernel. Returns (victims, last_search) or
        None when the native path is unavailable (no snapshot, no library,
        or a freed node the snapshot does not know — fall back to the
        Python search)."""
        engine = self.engine
        snap_of = getattr(engine, "_snap", None)
        snap = snap_of() if snap_of is not None else None
        lib = getattr(engine, "native", None)
        if snap is None or lib is None:
            return None
        import ctypes

        snap.ensure_dense()
        names = snap.names
        idx = snap._idx
        n = len(names)
        # All-zero state mask == ready, schedulable, not quarantined —
        # exactly the usable set the Python search probes against.
        flags = snap.pack_flags(quarantined, frozenset())
        usable = (ctypes.c_uint8 * max(1, n))(
            *[1 if flags[i] == 0 else 0 for i in range(n)]
        )
        target = req.spec.resource.target_node
        target_mode = target_idx = 0
        if target:
            ti = idx.get(target)
            if ti is not None and usable[ti]:
                target_mode, target_idx = 1, ti
            else:
                # Target set but gone/unusable: no combo is ever feasible
                # (the Python search's target_node-is-None case).
                target_mode = 2
        used_arr = snap.pack_used(used)
        ncand = len(candidates)
        cand_prio = (ctypes.c_int64 * ncand)(*[c.priority for c in candidates])
        cand_chips = (ctypes.c_int64 * ncand)(
            *[c.total_chips for c in candidates]
        )
        # Name ranks: rank order == name lexicographic order, so the
        # kernel's rank-sequence comparison is the tuple-of-names tiebreak.
        by_name = sorted(range(ncand), key=lambda i: candidates[i].name)
        ranks = [0] * ncand
        for r, i in enumerate(by_name):
            ranks[i] = r
        cand_rank = (ctypes.c_int32 * ncand)(*ranks)
        off = [0]
        fidx: List[int] = []
        famt: List[int] = []
        for c in candidates:
            for node, chips in c.freed.items():
                i = idx.get(node)
                if i is None:
                    return None  # freed node unknown to the snapshot
                fidx.append(i)
                famt.append(chips)
            off.append(len(fidx))
        freed_off = (ctypes.c_int32 * (ncand + 1))(*off)
        freed_idx = (ctypes.c_int32 * max(1, len(fidx)))(*fidx)
        freed_amt = (ctypes.c_int32 * max(1, len(famt)))(*famt)
        try:
            sel, info = lib.victims(
                n, snap._slots, used_arr, usable,
                snap._cpu, snap._mem, snap._eph, snap._pods,
                req.spec.resource.other_spec,
                shape.chips_per_host, shape.num_hosts,
                target_mode, target_idx,
                cand_prio, cand_chips, cand_rank,
                freed_off, freed_idx, freed_amt,
                _EXHAUSTIVE_MAX_CANDIDATES, _EXHAUSTIVE_MAX_SIZE,
            )
        except OSError:
            return None
        return [candidates[i].name for i in sel], info

    # ------------------------------------------------------------------
    def _greedy_prune(self, candidates, feasible) -> List[str]:
        """Add cheapest-first until feasible, then drop every member whose
        removal keeps feasibility — an irreducible set in O(n) probes."""
        chosen: List[_Candidate] = []
        for c in candidates:
            chosen.append(c)
            if feasible(tuple(chosen)):
                break
        else:
            return []
        # Prune most-expensive-first so the survivors skew cheap.
        for c in sorted(
            list(chosen),
            key=lambda c: (-c.priority, -c.total_chips, c.name),
        ):
            trial = [x for x in chosen if x is not c]
            if trial and feasible(tuple(trial)):
                chosen = trial
        return [c.name for c in chosen]

    # ------------------------------------------------------------------
    def _candidates(
        self, req: ComposabilityRequest, quarantined: Set[str]
    ) -> List[_Candidate]:
        usable: Set[str] = set()
        for n in self.store.list(Node):
            if (
                n.status.ready
                and not n.spec.unschedulable
                and n.metadata.name not in quarantined
            ):
                usable.add(n.metadata.name)
        children_by_owner: Dict[str, List[ComposableResource]] = {}
        existing_names: Set[str] = set()
        for c in self.store.list(ComposableResource):
            existing_names.add(c.name)
            if c.being_deleted:
                continue
            owner = c.metadata.labels.get(LABEL_MANAGED_BY, "")
            if owner:
                children_by_owner.setdefault(owner, []).append(c)

        out: List[_Candidate] = []
        for other in self.store.list(ComposabilityRequest):
            if other.name == req.name or other.being_deleted:
                continue
            if other.spec.priority >= req.spec.priority:
                continue
            if other.spec.preemption_policy == PREEMPT_NEVER:
                continue
            freed: Dict[str, int] = {}
            for c in children_by_owner.get(other.name, []):
                if c.spec.target_node in usable:
                    chips = c.spec.chip_count if c.spec.type == "tpu" else 1
                    freed[c.spec.target_node] = (
                        freed.get(c.spec.target_node, 0) + chips
                    )
            # Placeholder rows hold capacity exactly like children do in
            # used_slots_map — an Updating victim's claim must be evictable
            # too, or a half-created gang could never be preempted.
            per_member = (
                other.status.slice.chips_per_host
                if other.spec.resource.type == "tpu"
                and other.status.slice.chips_per_host
                else 1
            )
            for name, rs in other.status.resources.items():
                if name not in existing_names and rs.node_name in usable:
                    freed[rs.node_name] = freed.get(rs.node_name, 0) + per_member
            if not freed:
                continue  # nothing this victim frees is usable — skip it
            out.append(
                _Candidate(
                    name=other.name,
                    priority=other.spec.priority,
                    freed=freed,
                    total_chips=sum(freed.values()),
                    creation=other.metadata.creation_timestamp or "",
                )
            )
        return out
