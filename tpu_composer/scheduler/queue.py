"""Pending-request priority queue + conservative backfill gate.

Requests that cannot place right now register here (the scheduler facade
does it on every failed placement). The queue is the cross-request memory
the inline allocator never had: with it, a placement decision can consult
*who else is waiting* instead of handing capacity to whoever reconciles
first.

Admission discipline:

- **Gang admission** is structural — a multi-host slice's hosts are picked
  and reserved in one atomic decision (``PlacementEngine.pick_hosts`` +
  ``reserve_slice``), so a 2-host slice can never hold one host while
  waiting for the other and deadlock against a peer doing the same. The
  queue adds the cross-request half: whole-gang demands are recorded here
  so peers can see them.
- **Conservative backfill**: a lower-priority request may place only if the
  placement leaves every *currently-placeable* higher-priority pending
  request still placeable. A higher-priority request that cannot place
  either way (e.g. its only candidate hosts are quarantined) does NOT block
  the queue — that is exactly the priority-inversion case: holding everyone
  behind an unsatisfiable head-of-line demand would starve the cluster for
  nothing.

State is in-memory and rebuilt organically: every unplaced request
re-registers on each reconcile attempt, so a controller restart repopulates
the queue within one reconcile wave (the store's initial-list replay).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from tpu_composer.api.types import (
    ComposabilityRequest,
    REQUEST_STATE_EMPTY,
    REQUEST_STATE_NODE_ALLOCATING,
)


@dataclass
class PendingEntry:
    name: str
    priority: int
    num_hosts: int
    chips_per_host: int
    enqueued_at: float  # monotonic; survives re-registration
    # Host the demand is pinned to beyond the spec (a samenode request
    # with placed devices can only grow on its anchor) — "" = unpinned.
    anchor: str = ""
    # Hosts the demand can NOT use (a differentnode request's devices
    # exclude their own hosts from its growth) — feasibility probes that
    # counted them would overreport and drop the gate's protection.
    exclude_nodes: tuple = ()


class SchedulerQueue:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[str, PendingEntry] = {}

    def note_pending(
        self,
        req: ComposabilityRequest,
        num_hosts: int,
        chips_per_host: int,
        anchor: str = "",
        exclude_nodes: tuple = (),
    ) -> PendingEntry:
        """Record (or refresh) a request that failed to place, with its
        demand as (hosts × chips-per-host) — a slice shape, or a scalar
        request's device spread — plus the anchor host a samenode grow is
        pinned to. The original enqueue time is kept across
        re-registrations so time-to-placement measures the full wait, but
        priority/demand track the live spec."""
        with self._lock:
            prev = self._entries.get(req.name)
            entry = PendingEntry(
                name=req.name,
                priority=req.spec.priority,
                num_hosts=num_hosts,
                chips_per_host=chips_per_host,
                enqueued_at=prev.enqueued_at if prev else time.monotonic(),
                anchor=anchor,
                exclude_nodes=tuple(exclude_nodes),
            )
            self._entries[req.name] = entry
            return entry

    def note_placed(self, name: str) -> Optional[float]:
        """Dequeue after a successful placement; returns the seconds the
        request waited, or None if it was never pending (first-try place)."""
        with self._lock:
            entry = self._entries.pop(name, None)
        if entry is None:
            return None
        return max(0.0, time.monotonic() - entry.enqueued_at)

    def forget(self, name: str) -> None:
        with self._lock:
            self._entries.pop(name, None)

    def prune(self, store) -> None:
        """Drop entries whose request is gone, deleting, or no longer
        waiting for placement (it progressed past NodeAllocating)."""
        with self._lock:
            names = list(self._entries)
        for name in names:
            req = store.try_get(ComposabilityRequest, name)
            if (
                req is None
                or req.being_deleted
                or req.status.state
                not in (REQUEST_STATE_EMPTY, REQUEST_STATE_NODE_ALLOCATING)
            ):
                self.forget(name)

    def entries_above(self, priority: int) -> List[PendingEntry]:
        """Pending entries with strictly higher priority, highest first."""
        with self._lock:
            entries = [
                e for e in self._entries.values() if e.priority > priority
            ]
        entries.sort(key=lambda e: (-e.priority, e.enqueued_at, e.name))
        return entries

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)

    def snapshot(self) -> List[PendingEntry]:
        with self._lock:
            entries = list(self._entries.values())
        entries.sort(key=lambda e: (-e.priority, e.enqueued_at, e.name))
        return entries
