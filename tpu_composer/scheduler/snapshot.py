"""Chip-index snapshot — packed arrays maintained from watch events.

Every placement decision used to start with ``capacity_maps``'s two full
store scans (list every ComposableResource, list every
ComposabilityRequest), then the fit search and the ledger's candidate
scan each re-listed the Node collection. On a 5k-node index that is four
O(cluster) walks of deepcopied objects per decision, all under the
allocation lock — the per-replica ceiling BENCH_r10 profiled.

:class:`ChipIndexSnapshot` replaces the walks with incremental
maintenance: it subscribes to the store's watch stream once and folds
each event into

- a node table packed into flat ctypes arrays (free-chip counts,
  ICI/fabric coordinate from the trailing host index, a state bitmask,
  and the other-resource columns ``node_fits`` checks), name-sorted so
  array index order IS node-name lexicographic order — every
  ``(value, name)`` tiebreak in the pure-Python engine becomes a
  ``(value, index)`` tiebreak over the arrays, which is what makes the
  native kernel (native/tpusched.cc) bit-identical to the Python path;
- occupancy accounting equivalent to ``capacity_maps``: child claims,
  placeholder rows (status.resources entries whose child does not exist
  yet), and the per-request sparse maps needed to produce the
  ``occupied`` / ``without`` views for any excluded request in O(claims
  of that request) instead of O(cluster).

Consistency discipline
----------------------

The legacy engine re-reads the store per decision, which (through the
CachedClient's write-response folding, or the in-proc store's
synchronous reads) preserves the *placeholders visible under the
allocation lock* invariant. The snapshot preserves it two ways:

- it subscribes on the **base** store, where ``_notify`` runs
  synchronously inside each CRUD call — an in-proc write is in the watch
  queue before the write returns, so ``sync()`` at decision time is
  read-your-writes. CachedClient and BreakingStore wrappers are
  unwrapped (their watch fan-out is either async or merely proxied);
  a wrapper that can *drop* events (ChaosStore) disables the snapshot
  entirely and the engine stays on the legacy walks;
- on a wire store (KubeStore) the watch is asynchronous, so the
  scheduler additionally **assumes** its own successful placements
  (kube-scheduler's assume/bind split): ``assume()`` folds the granted
  hosts into occupancy immediately, and the assumption is superseded
  when the watch delivers the request's real placeholder rows (or
  dropped on deletion / TTL expiry as a backstop).

``TPUC_NATIVE_SCHED=0`` disables the snapshot (and the native kernel)
entirely; the engine then behaves exactly as before this layer existed.
"""

from __future__ import annotations

import ctypes
import queue as _queue
import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from tpu_composer.api.types import (
    ComposabilityRequest,
    ComposableResource,
    LABEL_MANAGED_BY,
    Node,
)

# Verdict codes shared by the native kernel (native/tpusched.cc), the
# pure-Python port below, and the engine's string rendering. Order is the
# node_verdict precedence.
V_OK = 0
V_EXCLUDED = 1
V_QUARANTINED = 2
V_NOT_READY = 3
V_CORDONED = 4
V_NO_PORTS = 5
V_NODE_RESOURCES = 6

VERDICT_STR = {
    V_OK: "ok",
    V_EXCLUDED: "excluded",
    V_QUARANTINED: "quarantined",
    V_NOT_READY: "not-ready",
    V_CORDONED: "cordoned",
    V_NODE_RESOURCES: "node-resources",
}

# State-mask bits (uint8 per node). The base mask carries the node's own
# condition; the per-decision copy ORs in quarantine/exclusion.
F_EXCLUDED = 1
F_QUARANTINED = 2
F_NOT_READY = 4
F_CORDONED = 8

#: Assumed-placement backstop: a granted placement whose placeholder rows
#: never materialize (controller crashed between grant and status write)
#: stops holding phantom capacity after this many seconds.
ASSUME_TTL_S = 30.0


def _watch_source(store):
    """The lossless event source behind ``store``, or None when there is
    none (snapshot must then stay disabled). CachedClient fans events out
    asynchronously after its cache apply and BreakingStore merely proxies,
    so both unwrap to their base; a ChaosStore can drop events on the
    simulated wire, which would silently diverge the accounting."""
    s = store
    for _ in range(4):
        name = type(s).__name__
        if name == "CachedClient":
            s = s.store
            continue
        if name == "BreakingStore":
            s = s._inner
            continue
        break
    if type(s).__name__ in ("Store", "KubeStore"):
        return s
    return None


def _bump(maps: Dict[str, Dict[str, int]], key: str, node: str, delta: int) -> None:
    inner = maps.get(key)
    if inner is None:
        if delta == 0:
            return
        maps[key] = {node: delta}
        return
    v = inner.get(node, 0) + delta
    if v:
        inner[node] = v
    else:
        inner.pop(node, None)
        if not inner:
            maps.pop(key, None)


def _dec(d: Dict[str, int], node: str, chips: int) -> None:
    v = d.get(node, 0) - chips
    if v:
        d[node] = v
    else:
        d.pop(node, None)


class ChipIndexSnapshot:
    """Watch-maintained chip index with packed-array views.

    Thread-safety: all mutation happens in :meth:`sync`, :meth:`assume`
    and :meth:`drop_assumed`, which callers run under the scheduler's
    allocation lock (the same discipline every legacy store walk relied
    on). The internal lock only guards attach/detach races.
    """

    def __init__(self, store, assume_ttl_s: float = ASSUME_TTL_S) -> None:
        self.store = store
        self.assume_ttl_s = assume_ttl_s
        self.active = False
        #: Bumped on every applied change; scan-reuse keys include it so a
        #: retained scan is only ever reused against identical state.
        self.version = 0

        # node name -> (slots, hidx, ready, unsched, cpu, mem, eph, pods)
        self._nodes: Dict[str, tuple] = {}
        # ALL ComposableResource names (incl. being-deleted) — the
        # placeholder test capacity_maps uses is "row name not in existing".
        self._cr_names: Set[str] = set()
        # live child name -> (target_node, chips, owner label)
        self._child: Dict[str, Tuple[str, int, str]] = {}
        # live request name -> {row name -> (node, per_member)}
        self._req_rows: Dict[str, Dict[str, Tuple[str, int]]] = {}
        # row name -> request names carrying a row of that name
        self._row_owners: Dict[str, Set[str]] = {}

        # Derived occupancy (all positive entries, zero-pruned):
        self._occ: Dict[str, int] = {}  # node -> children + placeholders + assumed
        self._req_ph: Dict[str, Dict[str, int]] = {}  # request -> its placeholder claims
        self._req_child: Dict[str, Dict[str, int]] = {}  # request -> its child claims
        self._assumed: Dict[str, Dict[str, int]] = {}
        self._assumed_at: Dict[str, float] = {}

        # Dense (name-sorted) arrays, rebuilt lazily on node-set changes.
        self._names: List[str] = []
        self._idx: Dict[str, int] = {}
        self._dense_dirty = True
        self._slots = self._hidx = self._flags = None
        self._cpu = self._mem = self._eph = self._pods = None
        self._occ_arr = None

        self._lock = threading.Lock()
        self._queues: list = []
        base = _watch_source(store)
        if base is None:
            return
        try:
            # Subscribe BEFORE the initial list: events racing the list
            # re-apply idempotently (every apply diffs against held state).
            for kind in (Node.KIND, ComposableResource.KIND,
                         ComposabilityRequest.KIND):
                self._queues.append((kind, base.watch(kind)))
            self._base = base
            self._rebuild_full()
            self.active = True
        except Exception:
            self._detach()

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def _detach(self) -> None:
        self.active = False
        base = getattr(self, "_base", None)
        for _, q in self._queues:
            try:
                if base is not None:
                    base.stop_watch(q)
            except Exception:
                pass
        self._queues = []

    def _rebuild_full(self) -> None:
        self._nodes.clear()
        self._cr_names.clear()
        self._child.clear()
        self._req_rows.clear()
        self._row_owners.clear()
        self._occ.clear()
        self._req_ph.clear()
        self._req_child.clear()
        # Assumptions survive a rebuild: re-fold them on top.
        for claims in self._assumed.values():
            for node, chips in claims.items():
                self._claim(node, chips)
        self._dense_dirty = True
        for n in self.store.list(Node):
            self._apply_node("ADDED", n)
        for c in self.store.list(ComposableResource):
            self._apply_child("ADDED", c)
        for r in self.store.list(ComposabilityRequest):
            self._apply_req("ADDED", r)
        self.version += 1

    # ------------------------------------------------------------------
    # event application (all idempotent: each apply diffs old vs new)
    # ------------------------------------------------------------------
    def _claim(self, node: str, chips: int) -> None:
        if not chips:
            return
        v = self._occ.get(node, 0) + chips
        if v:
            self._occ[node] = v
        else:
            self._occ.pop(node, None)
        if not self._dense_dirty:
            i = self._idx.get(node)
            if i is not None:
                self._occ_arr[i] += chips

    def _apply_node(self, etype: str, obj) -> None:
        name = obj.metadata.name
        if etype == "DELETED":
            if self._nodes.pop(name, None) is not None:
                self._dense_dirty = True
                self.version += 1
            return
        from tpu_composer.scheduler.placement import host_index

        hidx = host_index(name)
        row = (
            int(obj.status.tpu_slots),
            -1 if hidx is None else hidx,
            bool(obj.status.ready),
            bool(obj.spec.unschedulable),
            int(obj.status.milli_cpu),
            int(obj.status.memory),
            int(obj.status.ephemeral_storage),
            int(obj.status.allowed_pod_number),
        )
        if self._nodes.get(name) != row:
            self._nodes[name] = row
            self._dense_dirty = True
            self.version += 1

    def _retire_child(self, name: str) -> None:
        old = self._child.pop(name, None)
        if old is None:
            return
        node, chips, owner = old
        self._claim(node, -chips)
        if owner:
            _bump(self._req_child, owner, node, -chips)

    def _reflow_rows_named(self, row_name: str) -> None:
        """A child named ``row_name`` appeared or vanished: every request
        row of that name flips between placeholder and satisfied."""
        owners = self._row_owners.get(row_name)
        if not owners:
            return
        is_ph = row_name not in self._cr_names
        for req in owners:
            node, per = self._req_rows[req][row_name]
            delta = per if is_ph else -per
            self._claim(node, delta)
            _bump(self._req_ph, req, node, delta)

    def _apply_child(self, etype: str, obj) -> None:
        name = obj.metadata.name
        if etype == "DELETED":
            if name in self._cr_names:
                self._cr_names.discard(name)
                self._retire_child(name)
                self._reflow_rows_named(name)
                self.version += 1
            return
        if name not in self._cr_names:
            self._cr_names.add(name)
            self._reflow_rows_named(name)
        if obj.being_deleted:
            self._retire_child(name)
        else:
            node = obj.spec.target_node
            chips = obj.spec.chip_count if obj.spec.type == "tpu" else 1
            owner = obj.metadata.labels.get(LABEL_MANAGED_BY, "")
            new = (node, chips, owner)
            if self._child.get(name) != new:
                self._retire_child(name)
                self._child[name] = new
                self._claim(node, chips)
                if owner:
                    _bump(self._req_child, owner, node, chips)
        self.version += 1

    def _retire_req(self, name: str) -> None:
        for row, (node, per) in self._req_rows.pop(name, {}).items():
            owners = self._row_owners.get(row)
            if owners is not None:
                owners.discard(name)
                if not owners:
                    self._row_owners.pop(row, None)
            if row not in self._cr_names:
                self._claim(node, -per)
        self._req_ph.pop(name, None)

    def _apply_req(self, etype: str, obj) -> None:
        name = obj.metadata.name
        if etype == "DELETED" or obj.being_deleted:
            self._retire_req(name)
            self.drop_assumed(name)
            self.version += 1
            return
        res = obj.spec.resource
        per = (
            obj.status.slice.chips_per_host
            if res.type == "tpu" and obj.status.slice.chips_per_host
            else 1
        )
        new_rows = {
            rn: (rs.node_name, per)
            for rn, rs in obj.status.resources.items()
            if rs.node_name
        }
        old_rows = self._req_rows.get(name, {})
        if new_rows != old_rows:
            for row, (node, p) in old_rows.items():
                if row not in new_rows:
                    owners = self._row_owners.get(row)
                    if owners is not None:
                        owners.discard(name)
                        if not owners:
                            self._row_owners.pop(row, None)
                if row not in self._cr_names:
                    self._claim(node, -p)
                    _bump(self._req_ph, name, node, -p)
            for row, (node, p) in new_rows.items():
                self._row_owners.setdefault(row, set()).add(name)
                if row not in self._cr_names:
                    self._claim(node, p)
                    _bump(self._req_ph, name, node, p)
            if new_rows:
                self._req_rows[name] = new_rows
            else:
                self._req_rows.pop(name, None)
        if new_rows:
            # Real claims arrived — the assumption they supersede goes.
            self.drop_assumed(name)
        self.version += 1

    _APPLY = {
        Node.KIND: "_apply_node",
        ComposableResource.KIND: "_apply_child",
        ComposabilityRequest.KIND: "_apply_req",
    }

    # ------------------------------------------------------------------
    # decision-time API
    # ------------------------------------------------------------------
    def sync(self) -> None:
        """Drain the watch queues and fold every pending event in. Called
        at the top of each decision (capacity_maps); in-proc this is
        read-your-writes because _notify is synchronous inside CRUD."""
        if not self.active:
            return
        try:
            for kind, q in self._queues:
                apply = getattr(self, self._APPLY[kind])
                while True:
                    try:
                        ev = q.get(block=False)
                    except _queue.Empty:
                        break
                    if ev is None or getattr(ev, "obj", None) is None:
                        continue
                    apply(ev.type, ev.obj)
        except Exception:
            # A torn event stream means the accounting can no longer be
            # trusted; rebuild from a full list, or disable on failure.
            try:
                self._rebuild_full()
            except Exception:
                self._detach()
                return
        if self._assumed_at:
            now = time.monotonic()
            for name in [
                n for n, at in self._assumed_at.items()
                if now - at > self.assume_ttl_s
            ]:
                self.drop_assumed(name)

    def assume(self, request: str, claims: Dict[str, int]) -> None:
        """Fold a just-granted placement into occupancy before its status
        write lands (kube-scheduler's assume): node -> chips claimed."""
        if not self.active or not claims:
            return
        self.drop_assumed(request)
        self._assumed[request] = dict(claims)
        self._assumed_at[request] = time.monotonic()
        for node, chips in claims.items():
            self._claim(node, chips)
        self.version += 1

    def drop_assumed(self, request: str) -> None:
        claims = self._assumed.pop(request, None)
        self._assumed_at.pop(request, None)
        if claims:
            for node, chips in claims.items():
                self._claim(node, -chips)
            self.version += 1

    def capacity_views(
        self, exclude_request: str = ""
    ) -> Tuple[Dict[str, int], Dict[str, int]]:
        """The two dicts capacity_maps returns, from the accounting: the
        excluded request's placeholders (and assumed claims — its re-solve
        replaces those exactly like placeholders) come out of both views,
        its children out of ``without`` only."""
        occupied = dict(self._occ)
        if exclude_request:
            for node, chips in self._req_ph.get(exclude_request, {}).items():
                _dec(occupied, node, chips)
            for node, chips in self._assumed.get(exclude_request, {}).items():
                _dec(occupied, node, chips)
        without = dict(occupied)
        if exclude_request:
            for node, chips in self._req_child.get(exclude_request, {}).items():
                _dec(without, node, chips)
        return occupied, without

    # ------------------------------------------------------------------
    # packed views
    # ------------------------------------------------------------------
    @property
    def names(self) -> List[str]:
        self.ensure_dense()
        return self._names

    def ensure_dense(self) -> None:
        if not self._dense_dirty:
            return
        names = sorted(self._nodes)
        n = len(names)
        self._names = names
        self._idx = {nm: i for i, nm in enumerate(names)}
        rows = [self._nodes[nm] for nm in names]
        self._slots = (ctypes.c_int32 * n)(*[r[0] for r in rows])
        self._hidx = (ctypes.c_int32 * n)(*[r[1] for r in rows])
        self._flags = (ctypes.c_uint8 * n)(*[
            (0 if r[2] else F_NOT_READY) | (F_CORDONED if r[3] else 0)
            for r in rows
        ])
        self._cpu = (ctypes.c_int64 * n)(*[r[4] for r in rows])
        self._mem = (ctypes.c_int64 * n)(*[r[5] for r in rows])
        self._eph = (ctypes.c_int64 * n)(*[r[6] for r in rows])
        self._pods = (ctypes.c_int64 * n)(*[r[7] for r in rows])
        self._occ_arr = (ctypes.c_int32 * n)(*[
            self._occ.get(nm, 0) for nm in names
        ])
        self._dense_dirty = False

    def pack_used(self, used: Dict[str, int]):
        """A used-chips column aligned to the name-sorted node order, from
        any capacity view dict. O(claims), not O(nodes) — ctypes arrays
        zero-initialize. Claims on absent nodes are dropped, exactly as
        the legacy walk never consults them."""
        self.ensure_dense()
        arr = (ctypes.c_int32 * len(self._names))()
        idx = self._idx
        for name, v in used.items():
            i = idx.get(name)
            if i is not None:
                arr[i] = v
        return arr

    def pack_flags(self, quarantined: Set[str], exclude: Set[str]):
        """Per-decision state mask: the base node-condition bits plus this
        decision's quarantine/exclusion sets."""
        self.ensure_dense()
        n = len(self._names)
        arr = (ctypes.c_uint8 * n)()
        ctypes.memmove(arr, self._flags, n)
        idx = self._idx
        for name in quarantined:
            i = idx.get(name)
            if i is not None:
                arr[i] |= F_QUARANTINED
        for name in exclude:
            i = idx.get(name)
            if i is not None:
                arr[i] |= F_EXCLUDED
        return arr


# ----------------------------------------------------------------------
# pure-Python kernel — the bit-identical fallback for the native scan
# ----------------------------------------------------------------------
def py_scan(
    n: int,
    slots,
    used,
    hidx,
    flags,
    cpu,
    mem,
    eph,
    pods,
    other,  # OtherResourcesSpec or None
    chips: int,
    count: int,
):
    """One pass over the packed arrays producing exactly what the native
    ``tpus_scan`` produces: per-node clamped free chips, verdict codes,
    the candidate-verdicts ordering (fitting nodes in tightest-fit order,
    then rejected nodes), and — when ``count >= 1`` and enough nodes fit —
    the selected host indices (tightest-fit greedy refined by the
    ICI-contiguity window). Returns (num_ok, free, verdict, order, sel);
    ``sel`` is None when no selection was requested or possible."""
    free = [0] * n
    raw = [0] * n
    verdict = [0] * n
    ok: List[int] = []
    rejected: List[int] = []
    if other is not None:
        need_cpu = other.milli_cpu
        need_mem = other.memory
        need_eph = other.ephemeral_storage
        need_pods = other.allowed_pod_number
    for i in range(n):
        f = slots[i] - used[i]
        raw[i] = f
        free[i] = f if f > 0 else 0
        fl = flags[i]
        if fl & F_EXCLUDED:
            v = V_EXCLUDED
        elif fl & F_QUARANTINED:
            v = V_QUARANTINED
        elif fl & F_NOT_READY:
            v = V_NOT_READY
        elif fl & F_CORDONED:
            v = V_CORDONED
        elif f < chips:
            v = V_NO_PORTS
        elif other is not None and (
            cpu[i] < need_cpu or mem[i] < need_mem
            or eph[i] < need_eph or pods[i] < need_pods
        ):
            v = V_NODE_RESOURCES
        else:
            v = V_OK
            ok.append(i)
        verdict[i] = v
        if v != V_OK:
            rejected.append(i)
    # Tightest-fit order: least free-after-placement first; index order is
    # name order, so (free, i) == the legacy (free, name) tiebreak.
    ok.sort(key=lambda i: (raw[i], i))
    order = ok + rejected
    num_ok = len(ok)
    if count < 1 or num_ok < count:
        return num_ok, free, verdict, order, None
    greedy = ok[:count]
    if count == 1:
        return num_ok, free, verdict, order, greedy
    best_sum = sum(raw[i] for i in greedy)
    indexed = sorted(
        (i for i in ok if hidx[i] >= 0), key=lambda i: (hidx[i], i)
    )
    best = None  # (span, start_index, window)
    for s in range(len(indexed) - count + 1):
        window = indexed[s:s + count]
        if any(
            hidx[window[j]] == hidx[window[j + 1]] for j in range(count - 1)
        ):
            continue
        if sum(raw[i] for i in window) != best_sum:
            continue
        span = hidx[window[-1]] - hidx[window[0]] - (count - 1)
        key = (span, hidx[window[0]])
        if best is None or key < best[:2]:
            best = (span, hidx[window[0]], window)
    if best is not None:
        return num_ok, free, verdict, order, best[2]
    return num_ok, free, verdict, order, greedy
