"""tpu_composer.sim — the simulated-cluster layer.

Wire-level fakes and workload generators that exist to exercise the real
operator stack, promoted out of tests/ so they can be launched as standalone
processes (the proc-mode fleet) and driven by benches:

- ``apiserver``: the kube-apiserver fake speaking the real K8s wire protocol,
  launchable via ``python -m tpu_composer.sim.apiserver`` (tests/fake_apiserver
  re-exports it for the existing suites);
- ``churn``: the deterministic, seeded macro-scale churn generator driving
  thousands of concurrent ComposabilityRequests against a 5-10k-node
  simulated inventory.

Nothing here runs in production; cmd/main never imports it.
"""
