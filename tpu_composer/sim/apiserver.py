"""In-process kube-apiserver fake speaking the real K8s wire protocol.

The envtest analog for this repo (SURVEY.md §4 layer 1): the reference runs
every controller suite against a real kube-apiserver+etcd spun up per suite
(/root/reference/internal/controller/suite_test.go:357-385). We get the same
fidelity boundary — controllers talk HTTP/JSON to a server enforcing apiserver
semantics — without vendoring the binaries: this server implements

- typed REST: POST/GET/PUT/DELETE on ``/apis/<group>/<version>/<plural>``
  and ``/api/v1/nodes`` (core group);
- the status subresource (``PUT .../status`` only persists status);
- optimistic concurrency: stale ``resourceVersion`` → 409 Conflict,
  duplicate create → 409 AlreadyExists (Status body with ``reason`` set the
  way apimachinery does);
- finalizer-gated deletion: DELETE with finalizers present marks
  ``deletionTimestamp``; a PUT removing the last finalizer purges;
- spec-change generation bump; system-owned uid/creationTimestamp;
- ``?labelSelector=`` equality filtering on lists;
- ``?watch=true`` chunked streaming watches with ``resourceVersion``
  resume and JSON-per-line events, ADDED/MODIFIED/DELETED;
- the ``tpuc-mux/1`` framed transport (``GET /mux`` + Upgrade): every verb
  and watch of one client multiplexed over a single socket as
  length-prefixed JSON frames (runtime/wiremux.py defines the protocol),
  served alongside plain HTTP by the same verb plane.

Promoted from tests/fake_apiserver.py (which re-exports this module) so it
is launchable as a standalone shared store for the proc-mode fleet
(fleet/proc.py):

    python -m tpu_composer.sim.apiserver --nodes 8 --url-file /tmp/api.json

Concurrency contract (multi-process hardening): state is sharded per kind —
each path prefix owns a ``_KindState`` with its own lock, objects, watch
fanout, and bounded event log — so replicas writing different kinds never
serialize on each other (the pre-r11 single ``_State.lock`` made the sim
the fleet's scaling ceiling). Within one kind, every rv allocation, object
mutation, and watch-event publication happens under that kind's lock, so
the per-kind event log is totally ordered by rv no matter how many client
processes write in parallel; a CAS PUT observes-and-replaces atomically
(lost updates are impossible — one of two racing writers gets 409
Conflict). resourceVersions still come from one global monotonic counter
(its own small leaf lock), so rvs stay comparable across kinds exactly as
one etcd revision counter serves all keys. The listen backlog is sized for
whole fleets of replicas dialing at once.

Used by test_kubestore.py for the full operator e2e on a cluster-shaped API,
by bench.py's attach_cluster/proc_scaling benches, and by ProcFleet as the
shared wire-level store under real-OS-process replicas.
"""

from __future__ import annotations

import base64
import collections
import json
import ssl
import sys
import threading
import time
import urllib.request
import uuid
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Deque, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlparse

from tpu_composer.runtime import wiremux

#: Listen backlog. ThreadingHTTPServer's default request_queue_size of 5 is
#: tuned for one polite in-process client; a 4-replica proc fleet (each with
#: per-kind reflectors, lease renewers, and reconcile workers opening fresh
#: connections) can burst far past it and see ECONNREFUSED. Real apiservers
#: listen deep; so do we.
_LISTEN_BACKLOG = 128

#: Rolling cap on the wire-level request log. The log exists for
#: cache-efficiency assertions in unit tests (thousands of entries at most);
#: under a macro-scale churn bench it would otherwise grow without bound.
_REQUEST_LOG_CAP = 100_000

#: Verb workers per mux connection. Frames pipeline from every controller
#: thread of one replica; handling them serially would stack the injected
#: latency_s (the RTT model) request-by-request and erase the pipelining the
#: transport exists for. Sixteen matches a replica's plausible concurrent
#: verb count (reconcile workers + lease + telemetry + syncer).
_MUX_VERB_WORKERS = 16


def _apply_jsonpatch(obj: Dict[str, Any], patch: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Minimal RFC 6902 apply (add/replace/remove) — what a real apiserver
    does with a mutating webhook's JSONPatch response."""
    out = json.loads(json.dumps(obj))
    for op in patch:
        parts = [p.replace("~1", "/").replace("~0", "~")
                 for p in op["path"].lstrip("/").split("/")]
        parent = out
        for p in parts[:-1]:
            parent = parent[int(p)] if isinstance(parent, list) else parent.setdefault(p, {})
        leaf = parts[-1]
        if op["op"] in ("add", "replace"):
            if isinstance(parent, list):
                if leaf == "-":
                    parent.append(op["value"])
                else:
                    parent.insert(int(leaf), op["value"]) if op["op"] == "add" \
                        else parent.__setitem__(int(leaf), op["value"])
            else:
                parent[leaf] = op["value"]
        elif op["op"] == "remove":
            if isinstance(parent, list):
                parent.pop(int(leaf))
            else:
                parent.pop(leaf, None)
        else:
            raise ValueError(f"unsupported JSONPatch op {op['op']!r}")
    return out


def _status_doc(code: int, reason: str, message: str) -> Dict[str, Any]:
    return {
        "kind": "Status",
        "apiVersion": "v1",
        "status": "Failure",
        "code": code,
        "reason": reason,
        "message": message,
    }


def _status_body(code: int, reason: str, message: str) -> bytes:
    return json.dumps(_status_doc(code, reason, message)).encode()


class _Server(ThreadingHTTPServer):
    """ThreadingHTTPServer with a fleet-sized accept queue."""

    daemon_threads = True
    allow_reuse_address = True
    request_queue_size = _LISTEN_BACKLOG

    def handle_error(self, request, client_address):  # pragma: no cover
        # A SIGKILLed replica (proc-mode failover tests) tears down its
        # sockets mid-response; the resulting BrokenPipe/ConnectionReset in
        # the handler thread is expected churn, not a server bug. Everything
        # else keeps the stock stderr traceback.
        exc = sys.exc_info()[1]
        if isinstance(exc, ConnectionError):
            return
        super().handle_error(request, client_address)


class _KindState:
    """One kind's shard of the 'etcd': its own lock, objects by name, watch
    fanout, and a bounded event log with a per-kind compaction horizon."""

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.objects: Dict[str, Dict[str, Any]] = {}
        # watch subscribers: (buffer, condition) pairs
        self.watchers: List[Tuple[List[Dict[str, Any]], threading.Condition]] = []
        # True event history, exactly as etcd's WAL serves watch resumes:
        # (rv, type, object). A resume within the horizon replays real
        # events — including DELETED, which the current-state replay the
        # pre-r5 fake did could never produce.
        self.event_log: List[Tuple[int, str, Dict[str, Any]]] = []
        # Watches resuming from rv <= compacted_rv are answered with an
        # ERROR event carrying a 410 Status, like a compacted etcd.
        self.compacted_rv = 0


class _ObjectsView:
    """(prefix, name)-keyed dict facade over the per-kind shards.

    Harness code that predates sharding (tests, bench pollers, ProcFleet's
    shard/intent scans) reads and mutates ``state.objects`` as one flat
    dict; this view keeps that surface while each operation takes only the
    touched shard's lock. ``items()`` is a cross-shard snapshot — each
    shard internally consistent, shards read in sequence."""

    _MISSING = object()

    def __init__(self, state: "_State") -> None:
        self._state = state

    def items(self) -> List[Tuple[Tuple[str, str], Dict[str, Any]]]:
        out: List[Tuple[Tuple[str, str], Dict[str, Any]]] = []
        for prefix, ks in self._state.kinds():
            with ks.lock:
                out.extend(
                    ((prefix, name), obj)
                    for name, obj in sorted(ks.objects.items())
                )
        return out

    def keys(self) -> List[Tuple[str, str]]:
        return [k for k, _ in self.items()]

    def values(self) -> List[Dict[str, Any]]:
        return [v for _, v in self.items()]

    def __iter__(self):
        return iter(self.keys())

    def __len__(self) -> int:
        total = 0
        for _, ks in self._state.kinds():
            with ks.lock:
                total += len(ks.objects)
        return total

    def get(self, key: Tuple[str, str], default: Any = None) -> Any:
        prefix, name = key
        ks = self._state.kind(prefix)
        with ks.lock:
            return ks.objects.get(name, default)

    def __getitem__(self, key: Tuple[str, str]) -> Dict[str, Any]:
        out = self.get(key, self._MISSING)
        if out is self._MISSING:
            raise KeyError(key)
        return out

    def __contains__(self, key: Tuple[str, str]) -> bool:
        return self.get(key, self._MISSING) is not self._MISSING

    def __setitem__(self, key: Tuple[str, str], obj: Dict[str, Any]) -> None:
        prefix, name = key
        ks = self._state.kind(prefix)
        with ks.lock:
            ks.objects[name] = obj

    def __delitem__(self, key: Tuple[str, str]) -> None:
        prefix, name = key
        ks = self._state.kind(prefix)
        with ks.lock:
            del ks.objects[name]

    def pop(self, key: Tuple[str, str], *default: Any) -> Any:
        prefix, name = key
        ks = self._state.kind(prefix)
        with ks.lock:
            return ks.objects.pop(name, *default)


class _State:
    """The 'etcd' — one global rv counter over per-kind shards, each with
    its own objects, watch fanout, and bounded event log (real etcd
    compacts; a watch resuming from before the horizon gets 410 Expired).

    ``lock`` survives as the legacy coarse lock: external harnesses hold it
    around multi-step reads of ``objects``; the server's own verb paths use
    only the per-kind shard locks (that coarse lock serializing all
    replicas was the proc-scaling ceiling ROADMAP item 1 named)."""

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self._rv_lock = threading.Lock()
        self._rv = 0
        self._kinds: Dict[str, _KindState] = {}
        self._kinds_lock = threading.Lock()
        # (path_prefix, name)-keyed dict facade over the shards
        self.objects = _ObjectsView(self)

    @property
    def rv(self) -> int:
        return self._rv

    def kind(self, prefix: str) -> _KindState:
        with self._kinds_lock:
            ks = self._kinds.get(prefix)
            if ks is None:
                ks = self._kinds[prefix] = _KindState()
            return ks

    def kinds(self) -> List[Tuple[str, _KindState]]:
        with self._kinds_lock:
            return sorted(self._kinds.items())

    def next_rv(self) -> int:
        # Leaf lock (kind lock → rv lock): rvs stay globally comparable
        # across shards, like one etcd revision counter over all keys.
        with self._rv_lock:
            self._rv += 1
            return self._rv

    def notify(self, prefix: str, etype: str, obj: Dict[str, Any]) -> None:
        """Publish one event. Caller holds ``kind(prefix).lock`` — that is
        what totally orders the kind's event log by rv. ONE immutable
        snapshot is shared by the event log and every watcher buffer:
        nothing mutates a published event, so the per-watcher deep-copy
        the pre-proc fake did was O(watchers × object) for nothing."""
        ks = self.kind(prefix)
        snapshot = json.loads(json.dumps(obj))
        event = {"type": etype, "object": snapshot}
        ks.event_log.append(
            (int(snapshot["metadata"]["resourceVersion"]), etype, snapshot)
        )
        if len(ks.event_log) > 10_000:
            # Rolling auto-compaction, like etcd's: dropping history moves
            # the 410 horizon forward, so long soaks stay bounded and
            # clients resuming from far behind get the Expired persona.
            dropped = ks.event_log[:5_000]
            ks.event_log = ks.event_log[5_000:]
            ks.compacted_rv = max(ks.compacted_rv, dropped[-1][0])
        for buf, cond in list(ks.watchers):
            with cond:
                buf.append(event)
                cond.notify_all()

    def compact(self, up_to_rv: Optional[int] = None) -> None:
        """Discard event history ≤ up_to_rv (default: everything so far)
        in every shard. The next watch resume from inside the discarded
        range gets 410."""
        horizon = self._rv if up_to_rv is None else up_to_rv
        for _, ks in self.kinds():
            with ks.lock:
                ks.compacted_rv = max(ks.compacted_rv, horizon)
                ks.event_log = [e for e in ks.event_log if e[0] > horizon]


class FakeApiServer:
    """HTTP + mux kube-apiserver fake. ``resources`` maps path prefixes to
    config:

        {"/apis/tpu.composer.dev/v1alpha1/composabilityrequests":
             {"kind": "ComposabilityRequest"}, ...}

    Start with ``start()``; ``url`` gives the base endpoint. Objects can be
    seeded/inspected directly via ``put_object``/``get_object`` (the tests'
    equivalent of kubectl).
    """

    def __init__(self, resources: Dict[str, Dict[str, Any]]) -> None:
        self.resources = resources
        self.state = _State()
        self.fail_hooks: List[Any] = []  # callables (method, path) -> Optional[(code, reason, msg)]
        # Wire-level request log [(method, path)] — the envtest-style probe
        # for how chatty a client is (cache-efficiency assertions). Mux
        # verbs log the same (method, path) strings as HTTP ones, so the
        # assertions hold on either transport. Bounded: a macro-scale churn
        # run would otherwise hold every request ever.
        self.request_log: Deque[Tuple[str, str]] = collections.deque(
            maxlen=_REQUEST_LOG_CAP
        )
        # Admission webhook registrations, called out over the wire exactly
        # as a real apiserver would (the envtest WebhookInstallOptions
        # analog — /root/reference/internal/webhook/v1alpha1/
        # webhook_suite_test.go:74-144). Each entry:
        #   {"prefix": <resource path prefix>, "url": <webhook endpoint>,
        #    "operations": {"CREATE", "UPDATE"}}
        # A denied review fails the API call with 403; a JSONPatch response
        # is applied to the object before it is stored.
        self.webhooks: List[Dict[str, Any]] = []
        # Injected per-request latency (seconds) — models apiserver RTT for
        # latency benchmarks. Applied once per request on either transport
        # (streaming watch events after connect are push, not
        # request/response).
        self.latency_s: float = 0.0
        # Live streaming-watch sockets, for the socket-kill persona
        # (kill_watch_connections): a mid-stream TCP reset is how real
        # apiserver restarts/LB failovers present to client watches. A mux
        # connection carrying watches registers here too — killing it takes
        # the verbs down with the watches, exactly like an LB failover.
        self.active_watch_conns: List[Any] = []
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # TCP_NODELAY, like the real apiserver (Go's net stack enables
            # it on every accepted conn). Without it, keep-alive clients
            # stall ~40ms per request: the handler writes response headers
            # and body as separate small sends, and Nagle holds the second
            # until the client's delayed ACK — invisible on one-shot
            # connections, a 1.4x attach-p50 tax on pooled ones (the
            # BENCH_r10 keep-alive regression).
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):  # quiet
                pass

            def _deny(self, code: int, reason: str, message: str) -> None:
                self._send(code, _status_doc(code, reason, message))

            def _ok(self, payload: Dict[str, Any], code: int = 200) -> None:
                self._send(code, payload)

            def _send(self, code: int, payload: Dict[str, Any]) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _maybe_fail(self) -> bool:
                out = server._check_fail(self.command, self.path)
                if out:
                    self._deny(*out)
                    return True
                return False

            # ---- verbs (thin shims over the shared verb plane) ----
            def do_GET(self) -> None:
                if urlparse(self.path).path == wiremux.MUX_PATH:
                    return self._mux_session()
                if self._maybe_fail():
                    return
                routed = server._route_path(self.path)
                if not routed:
                    return self._deny(404, "NotFound", f"no route {self.path}")
                prefix, name, cfg, _ = routed
                qs = parse_qs(urlparse(self.path).query)
                if not name and qs.get("watch", ["false"])[0] == "true":
                    return self._watch(prefix, qs)
                code, payload = server.handle_verb("GET", self.path, None)
                self._send(code, payload)

            def _watch(self, prefix: str, qs: Dict[str, List[str]]) -> None:
                st = server.state
                ks = st.kind(prefix)
                since = int(qs.get("resourceVersion", ["0"])[0] or 0)
                buf, cond, expired = server._subscribe(prefix, since)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def _write(evt: Dict[str, Any]) -> None:
                    line = (json.dumps(evt) + "\n").encode()
                    self.wfile.write(f"{len(line):x}\r\n".encode())
                    self.wfile.write(line + b"\r\n")

                if expired is not None:
                    try:
                        _write(expired)
                        self.wfile.flush()
                    except (BrokenPipeError, ConnectionResetError, OSError):
                        pass
                    return
                with st.lock:
                    server.active_watch_conns.append(self.connection)
                try:
                    while not getattr(server, "_shutdown", False):
                        with cond:
                            if not buf:
                                cond.wait(timeout=0.5)
                            events, buf[:] = list(buf), []
                        for evt in events:
                            _write(evt)
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                finally:
                    with ks.lock:
                        ks.watchers = [
                            w for w in ks.watchers if w[0] is not buf
                        ]
                    with st.lock:
                        try:
                            server.active_watch_conns.remove(self.connection)
                        except ValueError:
                            pass

            def _read_body(self) -> Dict[str, Any]:
                n = int(self.headers.get("Content-Length", "0"))
                return json.loads(self.rfile.read(n) or b"{}")

            def do_POST(self) -> None:
                body = self._read_body()
                if self._maybe_fail():
                    return
                code, payload = server.handle_verb("POST", self.path, body)
                self._send(code, payload)

            def do_PUT(self) -> None:
                body = self._read_body()
                if self._maybe_fail():
                    return
                code, payload = server.handle_verb("PUT", self.path, body)
                self._send(code, payload)

            def do_DELETE(self) -> None:
                if self._maybe_fail():
                    return
                code, payload = server.handle_verb("DELETE", self.path, None)
                self._send(code, payload)

            # ---- tpuc-mux/1 framed transport ----
            def _mux_session(self) -> None:
                """Upgrade this connection to framed mode and serve it until
                EOF: verbs pipeline through a small worker pool, each watch
                gets a dedicated pusher thread (the HTTP equivalent is one
                handler thread per watch connection). All response and push
                frames serialize on one write lock."""
                self.send_response(101, "Switching Protocols")
                self.send_header("Upgrade", wiremux.PROTOCOL)
                self.send_header("Connection", "Upgrade")
                self.end_headers()
                self.wfile.flush()
                self.close_connection = True
                conn = self.connection
                wlock = threading.Lock()
                watch_stops: Dict[int, threading.Event] = {}
                pool = ThreadPoolExecutor(
                    max_workers=_MUX_VERB_WORKERS, thread_name_prefix="mux-verb"
                )

                def send(frame: Dict[str, Any]) -> None:
                    data = wiremux.encode_frame(frame)
                    with wlock:
                        conn.sendall(data)

                try:
                    while not getattr(server, "_shutdown", False):
                        frame = wiremux.read_frame(self.rfile)
                        if frame is None:
                            break
                        if "ping" in frame:
                            # Liveness probe: answered inline on the read
                            # loop, never through the verb pool, so pongs
                            # measure the wire — not modeled apiserver
                            # latency or fail-hook personas.
                            send({"pong": frame["ping"]})
                            continue
                        if "cancel" in frame:
                            stop = watch_stops.get(frame["cancel"])
                            if stop is not None:
                                stop.set()
                            continue
                        rid = frame.get("id")
                        method = frame.get("method", "GET")
                        path = frame.get("path", "")
                        qs = parse_qs(urlparse(path).query)
                        is_watch = (
                            method == "GET"
                            and qs.get("watch", ["false"])[0] == "true"
                        )
                        routed = server._route_path(path) if is_watch else None
                        if is_watch and routed and not routed[1]:
                            stop = threading.Event()
                            watch_stops[rid] = stop
                            threading.Thread(
                                target=server._mux_watch,
                                args=(rid, path, routed[0], send, stop, conn),
                                daemon=True,
                                name=f"mux-watch-{rid}",
                            ).start()
                        else:
                            pool.submit(
                                server._mux_verb, rid, method, path,
                                frame.get("body"), send,
                            )
                except (wiremux.MuxError, OSError, ValueError):
                    pass  # truncated/corrupt peer or dead socket: drop session
                finally:
                    for stop in watch_stops.values():
                        stop.set()
                    pool.shutdown(wait=False)

        self._handler_cls = Handler
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._shutdown = False

    # ------------------------------------------------------------------
    # shared verb plane (HTTP handlers and the mux endpoint both call in)
    # ------------------------------------------------------------------
    @staticmethod
    def _status(code: int, reason: str, message: str) -> Tuple[int, Dict[str, Any]]:
        return code, _status_doc(code, reason, message)

    def _check_fail(
        self, method: str, path: str
    ) -> Optional[Tuple[int, str, str]]:
        """Request-log + injected latency + fail-hook personas. Runs once
        per request on either transport, with identical (method, path)
        strings — so request-counting assertions and path-matching hooks
        (watch_blocker) can't tell mux from HTTP."""
        self.request_log.append((method, path))
        if self.latency_s:
            time.sleep(self.latency_s)
        # Snapshot: hooks are armed/disarmed from other threads (and,
        # proc-mode, while many handler threads are in here).
        for hook in list(self.fail_hooks):
            out = hook(method, path)
            if out:
                return out
        return None

    def _route_path(
        self, path: str
    ) -> Optional[Tuple[str, Optional[str], Dict[str, Any], bool]]:
        """→ (prefix, name|None, resource_cfg, is_status)"""
        parsed = urlparse(path)
        p = unquote(parsed.path).rstrip("/")
        for prefix, cfg in self.resources.items():
            if p == prefix:
                return prefix, None, cfg, False
            if p.startswith(prefix + "/"):
                rest = p[len(prefix) + 1 :]
                if rest.endswith("/status"):
                    return prefix, rest[: -len("/status")], cfg, True
                if "/" not in rest:
                    return prefix, rest, cfg, False
        return None

    def _admit(
        self,
        prefix: str,
        operation: str,
        obj: Dict[str, Any],
        old: Optional[Dict[str, Any]],
    ) -> Tuple[Optional[Dict[str, Any]], Optional[Tuple[int, Dict[str, Any]]]]:
        """Run registered webhooks over the wire. Returns (patched object,
        None) on admission, (None, (code, status)) on denial/failure."""
        for hook in list(self.webhooks):
            if hook["prefix"] != prefix:
                continue
            if operation not in hook.get("operations", {"CREATE", "UPDATE"}):
                continue
            review = {
                "apiVersion": "admission.k8s.io/v1",
                "kind": "AdmissionReview",
                "request": {
                    "uid": str(uuid.uuid4()),
                    "operation": operation,
                    "object": obj,
                    "oldObject": old,
                },
            }
            data = json.dumps(review).encode()
            req = urllib.request.Request(
                hook["url"], data=data, method="POST",
                headers={"Content-Type": "application/json"},
            )
            kwargs: Dict[str, Any] = {"timeout": 10}
            if hook["url"].startswith("https"):
                ctx = ssl.create_default_context()
                ctx.check_hostname = False
                ctx.verify_mode = ssl.CERT_NONE  # self-signed test certs
                kwargs["context"] = ctx
            try:
                with urllib.request.urlopen(req, **kwargs) as resp:
                    out = json.loads(resp.read())
            except (OSError, ValueError) as e:
                # failurePolicy: Fail — the reference's default for its
                # validating webhook.
                return None, self._status(
                    500, "InternalError",
                    f"webhook {hook['url']} unreachable: {e}",
                )
            response = out.get("response") or {}
            if not response.get("allowed", False):
                msg = ((response.get("status") or {}).get("message")
                       or "admission denied")
                return None, self._status(403, "Forbidden", msg)
            if response.get("patch"):
                patch = json.loads(base64.b64decode(response["patch"]))
                obj = _apply_jsonpatch(obj, patch)
        return obj, None

    def handle_verb(
        self, method: str, path: str, body: Optional[Dict[str, Any]]
    ) -> Tuple[int, Dict[str, Any]]:
        """One non-watch REST verb, transport-agnostic: (code, payload)."""
        routed = self._route_path(path)
        if not routed:
            return self._status(404, "NotFound", f"no route {path}")
        prefix, name, cfg, is_status = routed
        st = self.state
        ks = st.kind(prefix)

        if method == "GET":
            if name:
                with ks.lock:
                    obj = ks.objects.get(name)
                if obj is None:
                    return self._status(404, "NotFound", f"{name} not found")
                return 200, obj
            qs = parse_qs(urlparse(path).query)
            with ks.lock:
                items = [o for _, o in sorted(ks.objects.items())]
                # rv snapshotted while holding the kind lock: a list must
                # never advertise an rv newer than its contents for this
                # kind, or a watch resumed from it skips events (only
                # observable with parallel writer processes). Same-kind
                # writes serialize on ks.lock, so every event this kind
                # publishes after this snapshot carries rv > list_rv.
                list_rv = st.rv
            sel = qs.get("labelSelector", [None])[0]
            if sel:
                pairs = dict(kv.split("=", 1) for kv in sel.split(","))
                items = [
                    o
                    for o in items
                    if all(
                        (o["metadata"].get("labels") or {}).get(k) == v
                        for k, v in pairs.items()
                    )
                ]
            return 200, {
                "kind": cfg["kind"] + "List",
                "apiVersion": cfg.get("apiVersion", "v1"),
                "metadata": {"resourceVersion": str(list_rv)},
                "items": items,
            }

        if method == "POST":
            if name:
                return self._status(405, "MethodNotAllowed", "POST to item")
            obj = body if body is not None else {}
            meta = obj.setdefault("metadata", {})
            oname = meta.get("name", "")
            if not oname:
                return self._status(422, "Invalid", "metadata.name required")
            obj, denied = self._admit(prefix, "CREATE", obj, None)
            if denied is not None:
                return denied
            meta = obj.setdefault("metadata", {})
            with ks.lock:
                if oname in ks.objects:
                    return self._status(
                        409, "AlreadyExists", f"{oname} already exists"
                    )
                meta["uid"] = meta.get("uid") or str(uuid.uuid4())
                meta["resourceVersion"] = str(st.next_rv())
                meta["generation"] = 1
                meta.setdefault(
                    "creationTimestamp",
                    time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
                )
                meta.pop("deletionTimestamp", None)
                ks.objects[oname] = obj
                st.notify(prefix, "ADDED", obj)
            return 201, obj

        if method == "PUT":
            if not name:
                return self._status(405, "MethodNotAllowed", "PUT to collection")
            incoming = body if body is not None else {}
            # Admission sees spec updates, not status subresource writes
            # (matching real webhook rules scoped to the main resource).
            if not is_status:
                with ks.lock:
                    old = ks.objects.get(name)
                    old = json.loads(json.dumps(old)) if old else None
                incoming, denied = self._admit(prefix, "UPDATE", incoming, old)
                if denied is not None:
                    return denied
            with ks.lock:
                stored = ks.objects.get(name)
                if stored is None:
                    return self._status(404, "NotFound", f"{name} not found")
                in_rv = str(incoming.get("metadata", {}).get("resourceVersion", ""))
                if in_rv and in_rv != stored["metadata"]["resourceVersion"]:
                    return self._status(
                        409,
                        "Conflict",
                        f"resourceVersion {in_rv} != {stored['metadata']['resourceVersion']}",
                    )
                new = json.loads(json.dumps(stored))
                if is_status:
                    new["status"] = incoming.get("status", {})
                else:
                    spec_changed = incoming.get("spec") != stored.get("spec")
                    new["spec"] = incoming.get("spec", {})
                    # mutable metadata
                    im = incoming.get("metadata", {})
                    for k in ("labels", "annotations", "finalizers", "ownerReferences"):
                        if k in im:
                            new["metadata"][k] = im[k]
                        else:
                            new["metadata"].pop(k, None)
                    if spec_changed:
                        new["metadata"]["generation"] = (
                            int(stored["metadata"].get("generation", 1)) + 1
                        )
                new["metadata"]["resourceVersion"] = str(st.next_rv())
                if (
                    new["metadata"].get("deletionTimestamp")
                    and not new["metadata"].get("finalizers")
                ):
                    del ks.objects[name]
                    st.notify(prefix, "DELETED", new)
                    return 200, new
                ks.objects[name] = new
                st.notify(prefix, "MODIFIED", new)
                return 200, new

        if method == "DELETE":
            if not name:
                return self._status(405, "MethodNotAllowed", "DELETE collection")
            with ks.lock:
                stored = ks.objects.get(name)
                if stored is None:
                    return self._status(404, "NotFound", f"{name} not found")
                if stored["metadata"].get("finalizers"):
                    if not stored["metadata"].get("deletionTimestamp"):
                        new = json.loads(json.dumps(stored))
                        new["metadata"]["deletionTimestamp"] = time.strftime(
                            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
                        )
                        new["metadata"]["resourceVersion"] = str(st.next_rv())
                        ks.objects[name] = new
                        st.notify(prefix, "MODIFIED", new)
                        return 200, new
                    return 200, stored
                del ks.objects[name]
                # Deletion is a write: the DELETED event carries a fresh
                # rv (etcd semantics) so watch resumes ordered after older
                # MODIFIEDs still replay it.
                stored = json.loads(json.dumps(stored))
                stored["metadata"]["resourceVersion"] = str(st.next_rv())
                st.notify(prefix, "DELETED", stored)
                return 200, stored

        return self._status(405, "MethodNotAllowed", f"unsupported {method}")

    def _subscribe(
        self, prefix: str, since: int
    ) -> Tuple[List[Dict[str, Any]], threading.Condition, Optional[Dict[str, Any]]]:
        """Register a watch on one kind shard: (buffer, condition,
        expired_event|None). When the resume rv is behind the compaction
        horizon, nothing is registered and the 410 ERROR event to send is
        returned — a real apiserver answers 200 + ERROR, then ends the
        watch; the client must relist."""
        ks = self.state.kind(prefix)
        buf: List[Dict[str, Any]] = []
        cond = threading.Condition()
        with ks.lock:
            if since and since < ks.compacted_rv:
                return buf, cond, {
                    "type": "ERROR",
                    "object": {
                        "kind": "Status", "apiVersion": "v1",
                        "status": "Failure", "code": 410,
                        "reason": "Expired",
                        "message": (
                            f"too old resource version: {since} "
                            f"({ks.compacted_rv})"
                        ),
                    },
                }
            if since:
                # Faithful resume: replay the true event history — including
                # DELETED — exactly as etcd serves a watch from a historical
                # rv inside the horizon. Replay and subscription happen under
                # ONE lock hold, so a write landing while we replay is either
                # in the history we replay or in the buffer we just
                # subscribed — never both, never neither (the
                # lost-event/duplicate race a 4-process hammer exposes
                # immediately).
                for rv, etype, o in ks.event_log:
                    if rv > since:
                        buf.append({"type": etype, "object": o})
            else:
                # No resume rv: current state as ADDED (legacy
                # list+watch-from-now shape).
                for oname in sorted(ks.objects):
                    buf.append(
                        {"type": "ADDED",
                         "object": json.loads(json.dumps(ks.objects[oname]))}
                    )
            ks.watchers.append((buf, cond))
        return buf, cond, None

    # ------------------------------------------------------------------
    # mux request execution (called from per-session worker threads)
    # ------------------------------------------------------------------
    def _mux_verb(self, rid, method, path, body, send) -> None:
        fail = self._check_fail(method, path)
        code, payload = (
            self._status(*fail) if fail else self.handle_verb(method, path, body)
        )
        try:
            send({"id": rid, "code": code, "body": payload})
        except (wiremux.MuxError, OSError):
            pass  # session died; the read loop tears everything down

    def _mux_watch(self, rid, path, prefix, send, stop, conn) -> None:
        """One watch stream on a mux session: ack, then push events until
        the client cancels, the session dies, or the server shuts down."""
        st = self.state
        ks = st.kind(prefix)
        buf: Optional[List[Dict[str, Any]]] = None
        registered = False
        try:
            fail = self._check_fail("GET", path)
            if fail:
                code, payload = self._status(*fail)
                send({"id": rid, "code": code, "body": payload})
                return
            qs = parse_qs(urlparse(path).query)
            since = int(qs.get("resourceVersion", ["0"])[0] or 0)
            buf, cond, expired = self._subscribe(prefix, since)
            registered = expired is None
            send({"id": rid, "code": 200, "watch": True})
            if expired is not None:
                send({"watch": rid, "event": expired})
                return
            with st.lock:
                self.active_watch_conns.append(conn)
            try:
                while not self._shutdown and not stop.is_set():
                    with cond:
                        if not buf:
                            cond.wait(timeout=0.5)
                        events, buf[:] = list(buf), []
                    for evt in events:
                        send({"watch": rid, "event": evt})
            finally:
                with st.lock:
                    try:
                        self.active_watch_conns.remove(conn)
                    except ValueError:
                        pass
        except (wiremux.MuxError, OSError):
            pass
        finally:
            if registered and buf is not None:
                with ks.lock:
                    ks.watchers = [w for w in ks.watchers if w[0] is not buf]
            try:
                send({"watch": rid, "end": True})
            except (wiremux.MuxError, OSError):
                pass

    # ------------------------------------------------------------------
    def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self._httpd = _Server((host, port), self._handler_cls)
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="fake-apiserver"
        )
        self._thread.start()
        return self.url

    @property
    def url(self) -> str:
        assert self._httpd
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self) -> None:
        self._shutdown = True
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()

    # ------------------------------------------------------------------
    # hostile-wire personas (VERDICT r4 missing #3)
    # ------------------------------------------------------------------
    def compact(self, up_to_rv: Optional[int] = None) -> None:
        """Etcd compaction: discard watch history; resumes from inside the
        discarded range get a 410 Expired ERROR event and must relist."""
        self.state.compact(up_to_rv)

    def kill_watch_connections(self) -> int:
        """Socket-level reset of every live streaming watch (no clean HTTP
        end). Returns how many were killed."""
        import socket as _socket

        with self.state.lock:
            conns = list(self.active_watch_conns)
        for conn in conns:
            try:
                conn.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
        return len(conns)

    def sever_watches(self, settle_s: float = 0.3) -> None:
        """Kill live watch sockets until none remain for ``settle_s``.
        Meant to run with a ``watch_blocker`` armed: reconnects are refused,
        so quiescence is permanent — closes the race where a watch was
        between reconnects (or mid-handshake) at the instant of a single
        kill and survived into the 'gap'."""
        quiet_since = None
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if self.kill_watch_connections():
                quiet_since = None
            else:
                quiet_since = quiet_since or time.monotonic()
                if time.monotonic() - quiet_since >= settle_s:
                    return
            time.sleep(0.02)

    def watch_blocker(self):
        """A fail-hook that 503s watch (re)connection attempts while armed —
        appended to ``fail_hooks`` to hold the stream down during a gap:

            unblock = srv.watch_blocker()
            ... mutate world ...
            unblock()

        Matches on the path string, which is identical on both transports,
        so a mux client's re-watch is refused exactly like an HTTP one's.
        """
        def hook(method: str, path: str):
            if method == "GET" and "watch=true" in path:
                return (503, "ServiceUnavailable", "watch blocked by test")
            return None

        self.fail_hooks.append(hook)

        def unblock() -> None:
            try:
                self.fail_hooks.remove(hook)
            except ValueError:
                pass

        return unblock

    # ------------------------------------------------------------------
    # test-side kubectl
    # ------------------------------------------------------------------
    def put_object(self, prefix: str, obj: Dict[str, Any]) -> Dict[str, Any]:
        """Seed/replace an object directly (bypasses conflict checks)."""
        st = self.state
        name = obj["metadata"]["name"]
        ks = st.kind(prefix)
        with ks.lock:
            existed = name in ks.objects
            meta = obj.setdefault("metadata", {})
            meta.setdefault("uid", str(uuid.uuid4()))
            meta["resourceVersion"] = str(st.next_rv())
            meta.setdefault("generation", 1)
            meta.setdefault(
                "creationTimestamp", time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
            )
            ks.objects[name] = obj
            st.notify(prefix, "MODIFIED" if existed else "ADDED", obj)
        return obj

    def get_object(self, prefix: str, name: str) -> Optional[Dict[str, Any]]:
        ks = self.state.kind(prefix)
        with ks.lock:
            obj = ks.objects.get(name)
            return json.loads(json.dumps(obj)) if obj else None

    def delete_object(self, prefix: str, name: str) -> None:
        st = self.state
        ks = st.kind(prefix)
        with ks.lock:
            obj = ks.objects.pop(name, None)
            if obj:
                obj = json.loads(json.dumps(obj))
                obj["metadata"]["resourceVersion"] = str(st.next_rv())
                st.notify(prefix, "DELETED", obj)


def operator_resources(
    group: str, version: str, namespace: str = "tpu-composer-system"
) -> Dict[str, Dict[str, Any]]:
    """The standard route map for operator-on-cluster harnesses — ONE
    definition shared by the e2e fixtures, bench.py, and the proc-mode
    fleet so a new published resource can't silently diverge between them.
    ``namespace`` scopes the namespaced kinds (Leases, matching KubeStore's
    --namespace / TPUC_NAMESPACE routing)."""
    return {
        f"/apis/{group}/{version}/composabilityrequests": {
            "kind": "ComposabilityRequest", "apiVersion": f"{group}/{version}",
        },
        f"/apis/{group}/{version}/composableresources": {
            "kind": "ComposableResource", "apiVersion": f"{group}/{version}",
        },
        "/api/v1/nodes": {"kind": "Node", "apiVersion": "v1"},
        "/apis/resource.k8s.io/v1beta1/resourceslices": {
            "kind": "ResourceSlice", "apiVersion": "resource.k8s.io/v1beta1",
        },
        "/apis/resource.k8s.io/v1alpha3/devicetaintrules": {
            "kind": "DeviceTaintRule", "apiVersion": "resource.k8s.io/v1alpha3",
        },
        # The control-plane-infrastructure kinds: leader/shard Leases, fleet
        # telemetry snapshots, maintenance drains. In-proc suites drive these
        # through an in-memory Store, so the pre-proc fake never routed
        # them — a full cmd/main replica over the wire needs all three.
        "/apis/coordination.k8s.io/v1/namespaces/" + namespace + "/leases": {
            "kind": "Lease", "apiVersion": "coordination.k8s.io/v1",
        },
        f"/apis/{group}/{version}/fleettelemetries": {
            "kind": "FleetTelemetry", "apiVersion": f"{group}/{version}",
        },
        f"/apis/{group}/{version}/nodemaintenances": {
            "kind": "NodeMaintenance", "apiVersion": f"{group}/{version}",
        },
    }


def core_node_doc(name: str, chips: int = 4,
                  chip_resource: str = "tpu.composer.dev/chips") -> Dict[str, Any]:
    """A core-v1-shaped Node as kubelet would publish it."""
    return {
        "apiVersion": "v1",
        "kind": "Node",
        "metadata": {"name": name, "labels": {"kubernetes.io/hostname": name}},
        "status": {
            "allocatable": {
                "cpu": "8",
                "memory": "32Gi",
                "ephemeral-storage": "100Gi",
                "pods": "110",
                chip_resource: str(chips),
            },
            "conditions": [{"type": "Ready", "status": "True"}],
        },
    }


# ----------------------------------------------------------------------
# standalone launcher: python -m tpu_composer.sim.apiserver
# ----------------------------------------------------------------------
def main(argv: Optional[List[str]] = None) -> int:
    """Serve the fake apiserver (and optionally a fake fabric) as a
    standalone process — the shared store a ProcFleet of real operator
    replicas dials into. Prints one JSON line with the bound URLs (and
    writes it to --url-file for supervisors that redirect stdout)."""
    import argparse
    import signal
    import sys

    from tpu_composer import GROUP, VERSION

    p = argparse.ArgumentParser(
        prog="python -m tpu_composer.sim.apiserver",
        description="standalone kube-apiserver fake for proc-mode fleets",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0, help="0 = ephemeral")
    p.add_argument("--namespace", default="tpu-composer-system",
                   help="namespace for the namespaced routes (Leases)")
    p.add_argument("--nodes", type=int, default=0,
                   help="seed N Ready core-v1 Nodes (node-0000...)")
    p.add_argument("--chips", type=int, default=4, help="chips per seeded node")
    p.add_argument("--latency", type=float, default=0.0,
                   help="injected per-request latency (seconds)")
    p.add_argument("--fabric", action="store_true",
                   help="also serve a fake fabric (REST pool dialect) backed"
                        " by an InMemoryPool sized to the seeded inventory")
    p.add_argument("--fabric-chips", default="",
                   help="fabric pool inventory, MODEL=N[,MODEL=N...]"
                        " (default: tpu-v4 sized to nodes*chips)")
    p.add_argument("--url-file", default="",
                   help="write the JSON discovery line here too")
    args = p.parse_args(argv)

    srv = FakeApiServer(operator_resources(GROUP, VERSION, args.namespace))
    srv.latency_s = args.latency
    srv.start(host=args.host, port=args.port)
    for i in range(args.nodes):
        srv.put_object(
            "/api/v1/nodes", core_node_doc(f"node-{i:04d}", chips=args.chips)
        )

    fabric_url = None
    fabric_srv = None
    if args.fabric:
        from tpu_composer.fabric.inmem import InMemoryPool
        try:
            from tests.fake_fabric import FakeFabricServer
        except ImportError as e:
            print(f"--fabric needs tests/fake_fabric.py importable "
                  f"(run from the repo root): {e}", file=sys.stderr)
            srv.stop()
            return 2
        if args.fabric_chips:
            chips = {
                m: int(n)
                for m, n in (kv.split("=", 1)
                             for kv in args.fabric_chips.split(","))
            }
        else:
            chips = {"tpu-v4": max(args.nodes, 1) * args.chips}
        fabric_srv = FakeFabricServer(pool=InMemoryPool(chips=chips))
        fabric_url = fabric_srv.url

    discovery = {
        "apiserver": srv.url,
        "fabric": fabric_url,
        "namespace": args.namespace,
        "nodes": args.nodes,
    }
    line = json.dumps(discovery)
    print(line, flush=True)
    if args.url_file:
        with open(args.url_file, "w") as f:
            f.write(line + "\n")

    done = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: done.set())
    try:
        while not done.wait(0.5):
            pass
    finally:
        if fabric_srv is not None:
            fabric_srv.close()
        srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
