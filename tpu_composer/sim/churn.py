"""Macro-scale churn generator: deterministic open-loop request workloads.

The 32-GPU composable-system study (PAPERS.md 2404.06467) publishes scaling
*curves*; producing one needs a workload that is (a) open-loop — arrivals
don't wait for the system, so a slow control plane builds real queues —
(b) macroscopic — thousands of concurrent ComposabilityRequests churning
(arrive/cancel/resize/migrate) over a 5-10k-node inventory — and
(c) deterministic — the same seed must yield byte-identical event traces so
curve points and CI reruns are comparable.

Three layers, smallest to largest:

- ``generate_plan(seed, ...)`` → ``ChurnPlan``: the seeded event trace.
  Pure function of its arguments; ``plan.trace_digest()`` is the replay-
  determinism witness (same seed → same digest, asserted in CI).
- ``simulate(plan)``: a fast in-memory placement state machine that runs the
  plan at full macro scale (50k+ CRs over 5-10k nodes in seconds) and
  reports placements, queue-wait percentiles (in sim time), and goodput —
  the capacity model that sizes live runs and proves the generator itself
  sustains macro scale.
- ``ChurnDriver``: replays a (smaller) plan in real time against a live
  wire-level store (the sim apiserver) with real HTTP verbs — POST arrive,
  finalizer-honoring DELETE cancel, read-modify-write PUT resize with 409
  retry, NodeMaintenance post/delete for migrate. bench_proc_scaling drives
  1/2/4-process replica fleets with it.

Everything here is seeded ``random.Random``; wall clock never influences
the trace (only the driver's pacing).
"""

from __future__ import annotations

import hashlib
import heapq
import json
import os as _os
import threading
import time
import urllib.error
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple

import random as _random

from tpu_composer.runtime import wiremux

ARRIVE = "arrive"
CANCEL = "cancel"
RESIZE = "resize"
MIGRATE = "migrate"


@dataclass(frozen=True)
class ChurnEvent:
    """One open-loop event. ``name`` is the CR name (arrive/cancel/resize)
    or the node name (migrate). ``size`` is the initial chip count on
    arrive, the new chip count on resize, 0 otherwise."""

    at_s: float
    kind: str
    name: str
    model: str = ""
    size: int = 0

    def line(self) -> str:
        return f"{self.at_s:.6f} {self.kind} {self.name} {self.model} {self.size}"


@dataclass
class ChurnPlan:
    """A seeded, fully materialized event trace plus the inventory it is
    meant to run against. The digest is the determinism contract."""

    seed: int
    nodes: int
    chips_per_node: int
    duration_s: float
    requests: int
    events: List[ChurnEvent] = field(default_factory=list)

    def trace_digest(self) -> str:
        h = hashlib.sha256()
        h.update(
            f"{self.seed}/{self.nodes}/{self.chips_per_node}/"
            f"{self.duration_s}/{self.requests}\n".encode()
        )
        for ev in self.events:
            h.update(ev.line().encode())
            h.update(b"\n")
        return h.hexdigest()

    def counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for ev in self.events:
            out[ev.kind] = out.get(ev.kind, 0) + 1
        return out


def generate_plan(
    seed: int,
    requests: int = 200,
    duration_s: float = 10.0,
    nodes: int = 16,
    chips_per_node: int = 4,
    models: Tuple[str, ...] = ("tpu-v4",),
    min_size: int = 1,
    max_size: int = 8,
    cancel_frac: float = 0.15,
    resize_frac: float = 0.15,
    migrate_frac: float = 0.05,
) -> ChurnPlan:
    """Deterministic open-loop plan: ``requests`` arrivals uniform over
    ``duration_s``; ``cancel_frac`` of them get a later cancel,
    ``resize_frac`` a later size change, and ``migrate_frac`` (of the
    request count) node-drain events land on random nodes. Pure function
    of its arguments — no wall clock, no global RNG."""
    rng = _random.Random(seed)
    events: List[ChurnEvent] = []
    for i in range(requests):
        at = rng.uniform(0.0, duration_s)
        name = f"churn-{seed}-{i:06d}"
        model = models[rng.randrange(len(models))]
        size = rng.randint(min_size, max_size)
        events.append(ChurnEvent(at, ARRIVE, name, model, size))
        follow = rng.random()
        if follow < cancel_frac:
            # Cancel some time later — sometimes before the system could
            # plausibly have placed it (the racy cancel is the point).
            events.append(
                ChurnEvent(
                    min(at + rng.uniform(0.05, duration_s / 2), duration_s),
                    CANCEL, name,
                )
            )
        elif follow < cancel_frac + resize_frac:
            new_size = rng.randint(min_size, max_size)
            if new_size != size:
                events.append(
                    ChurnEvent(
                        min(at + rng.uniform(0.1, duration_s / 2), duration_s),
                        RESIZE, name, model, new_size,
                    )
                )
    for j in range(int(requests * migrate_frac)):
        node = f"node-{rng.randrange(nodes):04d}"
        events.append(
            ChurnEvent(rng.uniform(0.2, duration_s), MIGRATE, f"{node}", "", 0)
        )
    # Total order with a deterministic tie-break; a cancel/resize riding the
    # same instant as its arrive sorts after it (ARRIVE < others
    # alphabetically happens to hold, but be explicit).
    kind_rank = {ARRIVE: 0, RESIZE: 1, MIGRATE: 2, CANCEL: 3}
    events.sort(key=lambda e: (e.at_s, kind_rank[e.kind], e.name))
    return ChurnPlan(
        seed=seed,
        nodes=nodes,
        chips_per_node=chips_per_node,
        duration_s=duration_s,
        requests=requests,
        events=events,
    )


# ----------------------------------------------------------------------
# layer 2: the macro-scale placement state machine
# ----------------------------------------------------------------------
class _Inventory:
    """First-fit-decreasing-ish placement over free-chip counts, O(log n)
    per op via a lazy max-heap — 50k placements over 10k nodes must run in
    seconds, so no linear scans."""

    def __init__(self, nodes: int, chips_per_node: int) -> None:
        self.free = {f"node-{i:04d}": chips_per_node for i in range(nodes)}
        self._heap: List[Tuple[int, str]] = [
            (-c, n) for n, c in sorted(self.free.items())
        ]
        heapq.heapify(self._heap)

    def _push(self, node: str) -> None:
        heapq.heappush(self._heap, (-self.free[node], node))

    def take(self, size: int) -> Optional[str]:
        """Grab ``size`` chips from the fullest-free node (best-fit-enough
        and deterministic). Returns the node or None if nothing fits."""
        while self._heap:
            negc, node = self._heap[0]
            if -negc != self.free[node]:
                heapq.heappop(self._heap)  # stale lazy entry
                continue
            if -negc >= size:
                heapq.heappop(self._heap)
                self.free[node] -= size
                self._push(node)
                return node
            return None  # fullest-free can't fit ⇒ nothing can
        return None

    def give(self, node: str, size: int) -> None:
        self.free[node] += size
        self._push(node)


def simulate(plan: ChurnPlan) -> Dict[str, Any]:
    """Run the plan through an in-memory placement machine at full macro
    scale. Sim time == event time; a queued arrival's wait ends when a
    capacity-freeing event lets it place. Deterministic."""
    import collections

    inv = _Inventory(plan.nodes, plan.chips_per_node)
    placed: Dict[str, Tuple[str, int, float]] = {}  # name -> (node, size, t)
    # FIFO with tombstones: a cancel marks the name dead in O(1) and the
    # drain skips corpses — 20k-deep queues under 50k-CR churn make a
    # list-rebuild-per-cancel quadratic.
    queued: "collections.deque[Tuple[float, str, str, int]]" = collections.deque()
    queued_names: Dict[str, int] = {}  # name -> requested size (live entries)
    cancelled_before_place = 0
    waits: List[float] = []
    served_chip_s = 0.0
    requested_chip_s = 0.0
    migrated = 0
    resize_ok = 0
    resize_blocked = 0
    end_t = plan.duration_s

    def drain_queue(now: float) -> None:
        # FIFO head-of-line semantics: stop at the first non-fit so big
        # requests can't be starved by later small ones (matches the
        # scheduler's queue discipline closely enough for a capacity model).
        while queued:
            t0, name, model, size = queued[0]
            if name not in queued_names:  # cancelled while waiting
                queued.popleft()
                continue
            node = inv.take(size)
            if node is None:
                return
            queued.popleft()
            queued_names.pop(name, None)
            placed[name] = (node, size, now)
            waits.append(now - t0)

    for ev in plan.events:
        now = ev.at_s
        if ev.kind == ARRIVE:
            requested_chip_s += ev.size * max(0.0, end_t - now)
            node = inv.take(ev.size)
            if node is None:
                queued.append((now, ev.name, ev.model, ev.size))
                queued_names[ev.name] = ev.size
            else:
                placed[ev.name] = (node, ev.size, now)
                waits.append(0.0)
        elif ev.kind == CANCEL:
            if ev.name in placed:
                node, size, t_place = placed.pop(ev.name)
                served_chip_s += size * max(0.0, now - t_place)
                requested_chip_s -= size * max(0.0, end_t - now)
                inv.give(node, size)
                drain_queue(now)
            elif ev.name in queued_names:
                qsize = queued_names.pop(ev.name)
                cancelled_before_place += 1
                requested_chip_s -= qsize * max(0.0, end_t - now)
        elif ev.kind == RESIZE:
            if ev.name in placed:
                node, size, t_place = placed[ev.name]
                delta = ev.size - size
                if delta <= 0:
                    inv.give(node, -delta)
                    served_chip_s += size * max(0.0, now - t_place)
                    placed[ev.name] = (node, ev.size, now)
                    resize_ok += 1
                    drain_queue(now)
                elif inv.free[node] >= delta:
                    inv.free[node] -= delta
                    inv._push(node)
                    served_chip_s += size * max(0.0, now - t_place)
                    placed[ev.name] = (node, ev.size, now)
                    resize_ok += 1
                else:
                    resize_blocked += 1
        elif ev.kind == MIGRATE:
            # Drain the node: every placement on it moves elsewhere (or
            # queues if the fleet is full).
            victims = [
                (name, rec) for name, rec in placed.items() if rec[0] == ev.name
            ]
            victims.sort()
            for name, (node, size, t_place) in victims:
                served_chip_s += size * max(0.0, now - t_place)
                inv.give(node, size)
                dest = inv.take(size)
                if dest is None:
                    del placed[name]
                    queued.append((now, name, "", size))
                    queued_names[name] = size
                else:
                    placed[name] = (dest, size, now)
                    migrated += 1
            drain_queue(now)
    # Close the books at end of plan.
    for name, (node, size, t_place) in placed.items():
        served_chip_s += size * max(0.0, end_t - t_place)
    waits.sort()

    def pct(p: float) -> float:
        if not waits:
            return 0.0
        return waits[min(len(waits) - 1, int(p * (len(waits) - 1)))]

    return {
        "digest": plan.trace_digest(),
        "arrivals": sum(1 for e in plan.events if e.kind == ARRIVE),
        "placed_total": len(waits),
        "still_running": len(placed),
        "still_queued": len(queued),
        "cancelled_before_place": cancelled_before_place,
        "migrated_members": migrated,
        "resize_ok": resize_ok,
        "resize_blocked": resize_blocked,
        "queue_wait_p50_s": round(pct(0.50), 6),
        "queue_wait_p99_s": round(pct(0.99), 6),
        "goodput_ratio": (
            round(served_chip_s / requested_chip_s, 6)
            if requested_chip_s > 0 else None
        ),
    }


# ----------------------------------------------------------------------
# layer 3: the live wire driver
# ----------------------------------------------------------------------
class ChurnDriver:
    """Replays a plan against a live apiserver with real HTTP verbs, paced
    by wall clock (``time_scale`` stretches the plan's timeline). Arrival
    wall times land in ``arrive_wall`` so the harness can compute real
    queue waits from observed Running transitions."""

    def __init__(
        self,
        base_url: str,
        plan: ChurnPlan,
        group: str,
        version: str,
        time_scale: float = 1.0,
        migrate_dwell_s: float = 1.0,
        wire_mux: Optional[bool] = None,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.plan = plan
        self.cr_prefix = f"/apis/{group}/{version}/composabilityrequests"
        self.nm_prefix = f"/apis/{group}/{version}/nodemaintenances"
        self.group_version = f"{group}/{version}"
        self.time_scale = time_scale
        self.migrate_dwell_s = migrate_dwell_s
        self.arrive_wall: Dict[str, float] = {}
        self.errors: List[str] = []
        self.sent: Dict[str, int] = {}
        self._stop = threading.Event()
        self._mx_seq = 0
        # Framed transport for the driver's own verbs (same kill switch as
        # KubeStore). ROADMAP item 1 fingered the per-request urllib cost —
        # connect + header parse per verb, in the driver process — as
        # driver overhead distorting the scaling curve; one framed socket
        # removes it. Timer-thread migrate deletes share it safely
        # (MuxClient pipelines across threads).
        if wire_mux is None:
            wire_mux = _os.environ.get("TPUC_WIRE_MUX", "1") != "0"
        self._mux: Optional[wiremux.MuxClient] = None
        self._mux_failed = not wire_mux

    # -- tiny wire client (stdlib only; the driver must not depend on
    #    KubeStore so driver cost never shadows what we're measuring) -----
    def _req(self, method: str, path: str,
             body: Optional[Dict[str, Any]] = None) -> Tuple[int, Dict[str, Any]]:
        if not self._mux_failed:
            try:
                if self._mux is None:
                    self._mux = wiremux.MuxClient(self.base_url)
                return self._mux.request(method, path, body=body, timeout=10.0)
            except wiremux.MuxHTTPError as e:
                return e.code, e.body
            except wiremux.MuxUnsupported:
                self._mux_failed = True  # plain-HTTP server: fall through
            except wiremux.MuxError as e:
                return 599, {"message": str(e)}
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                payload = json.loads(e.read() or b"{}")
            except ValueError:
                payload = {}
            return e.code, payload

    def _arrive(self, ev: ChurnEvent) -> None:
        code, _ = self._req("POST", self.cr_prefix, {
            "apiVersion": self.group_version,
            "kind": "ComposabilityRequest",
            "metadata": {"name": ev.name},
            "spec": {"resource": {"type": "tpu", "model": ev.model,
                                  "size": ev.size}},
        })
        if code == 201:
            self.arrive_wall[ev.name] = time.monotonic()
        else:
            self.errors.append(f"arrive {ev.name}: HTTP {code}")

    def _cancel(self, ev: ChurnEvent) -> None:
        code, _ = self._req("DELETE", f"{self.cr_prefix}/{ev.name}")
        if code not in (200, 404):
            self.errors.append(f"cancel {ev.name}: HTTP {code}")

    def _resize(self, ev: ChurnEvent) -> None:
        # Read-modify-write with CAS retry: exactly what kubectl edit does.
        for _ in range(8):
            code, obj = self._req("GET", f"{self.cr_prefix}/{ev.name}")
            if code != 200:
                return  # already cancelled/purged: benign churn
            obj.setdefault("spec", {}).setdefault("resource", {})["size"] = ev.size
            code, _ = self._req(
                "PUT", f"{self.cr_prefix}/{ev.name}", obj)
            if code == 200:
                return
            if code != 409:
                self.errors.append(f"resize {ev.name}: HTTP {code}")
                return
        self.errors.append(f"resize {ev.name}: conflict-retry budget spent")

    def _migrate(self, ev: ChurnEvent) -> None:
        self._mx_seq += 1
        name = f"churn-mx-{self.plan.seed}-{self._mx_seq:04d}"
        code, _ = self._req("POST", self.nm_prefix, {
            "apiVersion": self.group_version,
            "kind": "NodeMaintenance",
            "metadata": {"name": name},
            "spec": {"node_name": ev.name, "reason": "churn drain"},
        })
        if code != 201:
            self.errors.append(f"migrate {ev.name}: HTTP {code}")
            return

        def _lift() -> None:
            self._req("DELETE", f"{self.nm_prefix}/{name}")

        t = threading.Timer(self.migrate_dwell_s, _lift)
        t.daemon = True
        t.start()

    def run(self) -> Dict[str, int]:
        """Replay to completion (or ``stop()``). Open loop: pacing follows
        the plan clock only — a backed-up control plane builds real queues."""
        t0 = time.monotonic()
        handlers: Dict[str, Callable[[ChurnEvent], None]] = {
            ARRIVE: self._arrive, CANCEL: self._cancel,
            RESIZE: self._resize, MIGRATE: self._migrate,
        }
        for ev in self.plan.events:
            due = t0 + ev.at_s * self.time_scale
            while not self._stop.is_set():
                delay = due - time.monotonic()
                if delay <= 0:
                    break
                self._stop.wait(min(delay, 0.1))
            if self._stop.is_set():
                break
            handlers[ev.kind](ev)
            self.sent[ev.kind] = self.sent.get(ev.kind, 0) + 1
        # Leave the mux socket open until the dwell timers (migrate lifts)
        # have had their say; close() below is the explicit teardown.
        return dict(self.sent)

    def stop(self) -> None:
        self._stop.set()

    def close(self) -> None:
        mux, self._mux = self._mux, None
        self._mux_failed = True
        if mux is not None:
            mux.close()
