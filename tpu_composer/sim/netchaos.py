"""TCP-level chaos proxy — wire faults the verb-layer chaos can't model.

ChaosStore and ChaosFabricProvider inject at the VERB layer: a call fails,
a call is slow, a watch drops. But the failure class that dominates tight
RPC paths in production (Dagger, PAPERS.md 2106.01482) lives a layer
down — half-open sockets, NAT table drops, asymmetric routing, slow-loris
peers — where the OS never tells anyone the peer is gone and every verb
ever sent is simply ambiguous. Every soak before this one killed replicas
with ``kill -9``, where the kernel closes sockets for us; this proxy makes
the network itself lie.

:class:`ChaosProxy` is a real listening socket interposed between one
replica and the sim apiserver (ProcFleet points the replica's kubeconfig
at it), with per-connection pump threads and scriptable faults:

- ``cut()`` — hard RST on every live connection (SO_LINGER 0).
- ``partition(direction)`` — silent drop: the pump stops READING its
  source for the dark direction(s), so bytes vanish from the receiver's
  view while the sender's kernel buffer backs up and eventually its
  ``send`` blocks — exactly the half-open stall the mux send-timeout and
  ping deadline exist for. ``"c2s"``/``"s2c"``/``"both"``; new
  connections during a partition are accepted-but-dark (half-open), never
  connection-refused — refusal is a FAST failure and would let the client
  cheat.
- ``heal()`` — clear partitions/stalls (latency and throttle persist
  until cleared explicitly; they model link quality, not outage).
- ``latency(seconds, jitter, direction)`` — per-direction added delay.
- ``throttle(direction, bytes_per_s)`` — slow-loris: dribble bytes.
- ``truncate_next(n, direction)`` — forward exactly ``n`` more bytes,
  then RST: a frame cut mid-body.
- ``corrupt_next(direction)`` — XOR the next 4 bytes forwarded: a
  corrupt length prefix (the 64MB frame-cap guard's reason to exist).

All timing uses ``time.monotonic``; jitter comes from a seeded
``random.Random`` so soaks replay deterministically.
"""

from __future__ import annotations

import logging
import random
import select
import socket
import struct
import threading
import time
from typing import Dict, List, Optional

log = logging.getLogger("netchaos")

#: Forwarding directions.
C2S = "c2s"  # client -> server (replica -> apiserver)
S2C = "s2c"  # server -> client (apiserver -> replica)
BOTH = "both"

_LINGER_RST = struct.pack("ii", 1, 0)

#: Pump wakeup quantum: fault flips (partition/heal) take effect within
#: this bound even on an otherwise idle direction.
_TICK = 0.05


def _rst(sock: socket.socket) -> None:
    """Close with RST instead of FIN — the 'hard cut' fault."""
    try:
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER, _LINGER_RST)
    except OSError:
        pass
    try:
        sock.close()
    except OSError:
        pass


class _DirState:
    """Fault state for one forwarding direction of one proxy."""

    def __init__(self) -> None:
        self.dark = False
        self.latency = 0.0
        self.jitter = 0.0
        self.throttle_bps = 0.0
        self.truncate_after: Optional[int] = None
        self.corrupt_next = False


class _ProxyConn:
    """One proxied TCP connection: client socket, server socket, 2 pumps."""

    _ids = 0

    def __init__(self, proxy: "ChaosProxy", client: socket.socket,
                 server: socket.socket) -> None:
        self.proxy = proxy
        self.client = client
        self.server = server
        self.closed = threading.Event()
        _ProxyConn._ids += 1
        cid = _ProxyConn._ids
        self._threads = [
            threading.Thread(
                target=self._pump, args=(client, server, C2S),
                daemon=True, name=f"netchaos-c2s-{cid}",
            ),
            threading.Thread(
                target=self._pump, args=(server, client, S2C),
                daemon=True, name=f"netchaos-s2c-{cid}",
            ),
        ]
        for t in self._threads:
            t.start()

    def _pump(self, src: socket.socket, dst: socket.socket,
              direction: str) -> None:
        proxy = self.proxy
        while not self.closed.is_set():
            state = proxy._dirs[direction]
            # Dark check BEFORE the read: a partitioned direction must not
            # drain its source — the sender's kernel buffer fills and its
            # send() eventually blocks, which is what a real half-open
            # stall does (and what the mux send-timeout must survive).
            if state.dark:
                time.sleep(_TICK)
                continue
            try:
                readable, _, _ = select.select([src], [], [], _TICK)
            except (OSError, ValueError):
                break
            if not readable:
                continue
            try:
                data = src.recv(65536)
            except OSError:
                break
            if not data:
                break
            rst_after = False
            with proxy._lock:
                if state.dark:
                    # Partition raced the blocking read: the pump was parked
                    # in recv() when the direction went dark, so this chunk
                    # was read before the loop-top check could stop it.
                    # Silent-drop it rather than let one in-flight frame
                    # slip through the partition.
                    continue
                if state.corrupt_next:
                    state.corrupt_next = False
                    n = min(4, len(data))
                    data = bytes(b ^ 0xFF for b in data[:n]) + data[n:]
                if state.truncate_after is not None:
                    if len(data) >= state.truncate_after:
                        data = data[: state.truncate_after]
                        state.truncate_after = None
                        rst_after = True
                    else:
                        state.truncate_after -= len(data)
                delay = state.latency
                if state.jitter:
                    delay += proxy._rand.uniform(0.0, state.jitter)
                bps = state.throttle_bps
            if delay > 0:
                time.sleep(delay)
            try:
                if bps > 0:
                    # Slow-loris: dribble small chunks at the target rate.
                    chunk = max(1, int(bps * _TICK))
                    for off in range(0, len(data), chunk):
                        if self.closed.is_set():
                            return
                        dst.sendall(data[off: off + chunk])
                        time.sleep(_TICK)
                else:
                    dst.sendall(data)
            except OSError:
                break
            if rst_after:
                self.rst()
                return
        self.close()

    def rst(self) -> None:
        """Hard-cut this connection: RST both sides."""
        if not self.closed.is_set():
            self.closed.set()
            _rst(self.client)
            _rst(self.server)

    def close(self) -> None:
        if not self.closed.is_set():
            self.closed.set()
            for sock in (self.client, self.server):
                try:
                    sock.close()
                except OSError:
                    pass


class ChaosProxy:
    """Scriptable TCP fault injector between one client and one server.

    Listens on an ephemeral 127.0.0.1 port; every accepted connection is
    pumped to ``(target_host, target_port)`` through the fault state.
    Point a replica's kubeconfig ``server:`` at :attr:`url` and drive the
    faults from the test/fleet supervisor.
    """

    def __init__(self, target_host: str, target_port: int,
                 listen_host: str = "127.0.0.1", seed: int = 0) -> None:
        self.target = (target_host, target_port)
        self._lock = threading.Lock()
        self._dirs: Dict[str, _DirState] = {C2S: _DirState(), S2C: _DirState()}
        self._conns: List[_ProxyConn] = []
        self._rand = random.Random(seed)
        self._stopped = threading.Event()
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((listen_host, 0))
        self._listener.listen(64)
        self.host, self.port = self._listener.getsockname()[:2]
        self._accepter = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"netchaos-accept-{self.port}",
        )
        self._accepter.start()

    # -- wiring --------------------------------------------------------
    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def _accept_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                client, _ = self._listener.accept()
            except OSError:
                return  # listener closed by stop()
            try:
                client.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
            # Dial the real server even mid-partition: a refused connect
            # is a fast, honest failure — a partition must present as
            # accepted-but-dark (half-open) instead.
            try:
                server = socket.create_connection(self.target, timeout=5.0)
                server.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                client.close()
                continue
            conn = _ProxyConn(self, client, server)
            with self._lock:
                self._conns = [c for c in self._conns
                               if not c.closed.is_set()]
                self._conns.append(conn)

    def connections(self) -> int:
        with self._lock:
            return sum(1 for c in self._conns if not c.closed.is_set())

    # -- faults --------------------------------------------------------
    def _targets(self, direction: str) -> List[_DirState]:
        if direction == BOTH:
            return [self._dirs[C2S], self._dirs[S2C]]
        return [self._dirs[direction]]

    def cut(self) -> None:
        """RST every live proxied connection right now."""
        with self._lock:
            conns = list(self._conns)
        log.info("netchaos %s: cut (%d conns)", self.port, len(conns))
        for c in conns:
            c.rst()

    def partition(self, direction: str = BOTH) -> None:
        """Silent drop on ``direction`` — bytes vanish, sockets stay."""
        log.info("netchaos %s: partition %s", self.port, direction)
        with self._lock:
            for st in self._targets(direction):
                st.dark = True

    def heal(self) -> None:
        """End partitions/stalls and pending truncations/corruptions."""
        log.info("netchaos %s: heal", self.port)
        with self._lock:
            for st in self._dirs.values():
                st.dark = False
                st.truncate_after = None
                st.corrupt_next = False

    def latency(self, seconds: float, jitter: float = 0.0,
                direction: str = BOTH) -> None:
        """Add forwarding delay (seeded jitter on top) to ``direction``."""
        with self._lock:
            for st in self._targets(direction):
                st.latency = max(0.0, seconds)
                st.jitter = max(0.0, jitter)

    def throttle(self, direction: str = BOTH,
                 bytes_per_s: float = 0.0) -> None:
        """Slow-loris ``direction`` to ``bytes_per_s`` (0 = unthrottled)."""
        with self._lock:
            for st in self._targets(direction):
                st.throttle_bps = max(0.0, bytes_per_s)

    def truncate_next(self, n: int, direction: str = C2S) -> None:
        """Forward exactly ``n`` more bytes on ``direction``, then RST —
        a frame cut mid-body."""
        with self._lock:
            for st in self._targets(direction):
                st.truncate_after = max(0, int(n))

    def corrupt_next(self, direction: str = S2C) -> None:
        """XOR the next 4 bytes forwarded on ``direction`` — a corrupt
        frame length prefix."""
        with self._lock:
            for st in self._targets(direction):
                st.corrupt_next = True

    # -- lifecycle -----------------------------------------------------
    def stop(self) -> None:
        self._stopped.set()
        try:
            self._listener.close()
        except OSError:
            pass
        with self._lock:
            conns = list(self._conns)
            self._conns = []
        for c in conns:
            c.close()

    close = stop
