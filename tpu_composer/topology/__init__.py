"""Slice topology solving — the TPU-native allocation core.

The reference allocates N *independent* devices one at a time
(composabilityrequest_controller.go:361-467). TPU chips are only useful as a
*connected* ICI topology, so ``size`` must solve to a valid slice shape placed
all-or-nothing across hosts (SURVEY.md §5 "slice topology", §7 hard-part #1).
"""

from tpu_composer.topology.slices import (
    SliceShape,
    TopologyError,
    TpuModel,
    TPU_MODELS,
    is_tpu_model,
    solve_slice,
)

__all__ = [
    "SliceShape",
    "TopologyError",
    "TpuModel",
    "TPU_MODELS",
    "is_tpu_model",
    "solve_slice",
]
