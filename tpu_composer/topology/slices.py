"""Chip-count → ICI slice-shape solver.

Models the generation-specific constraints that make a TPU slice valid:

- each TPU generation has an ICI dimensionality (3D torus for v4/v5p, 2D for
  v5e/v6e) and a fixed chips-per-host;
- a multi-host slice's chip count must tile whole hosts, and every torus
  dimension must be a power of two (wrap-around links come in powers of two on
  the optical switch fabric);
- sub-host counts (1 chip, or 2 on 3D generations) are "standalone" shapes
  with no torus requirement.

The solver prefers the most compact (closest-to-cube) shape because compact
tori minimize the worst-case hop count and maximize bisection bandwidth —
which is what the allreduce north-star metric in BASELINE.md rewards.

Reference contrast: the reference has no analog — its node allocator
(composabilityrequest_controller.go:361-467) treats devices as independent
scalars. This module is the "single largest semantic change" SURVEY.md §5
calls out.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


class TopologyError(ValueError):
    pass


@dataclass(frozen=True)
class TpuModel:
    """Per-generation fabric constraints."""

    name: str
    ici_dims: int  # 3 = 3D torus (v4/v5p), 2 = 2D (v5e/v6e)
    chips_per_host: int
    max_chips: int
    # Chip counts allowed below one full host (no torus formed).
    standalone_counts: Tuple[int, ...]
    # How one host's chips are arranged on the ICI mesh (the single-full-host
    # slice shape, e.g. v4's 2x2x1 tray).
    host_dims: Tuple[int, ...] = ()


TPU_MODELS: Dict[str, TpuModel] = {
    m.name: m
    for m in (
        TpuModel("tpu-v4", ici_dims=3, chips_per_host=4, max_chips=4096,
                 standalone_counts=(1, 2), host_dims=(2, 2, 1)),
        TpuModel("tpu-v5p", ici_dims=3, chips_per_host=4, max_chips=8960,
                 standalone_counts=(1, 2), host_dims=(2, 2, 1)),
        TpuModel("tpu-v5e", ici_dims=2, chips_per_host=8, max_chips=256,
                 standalone_counts=(1, 2, 4), host_dims=(2, 4)),
        TpuModel("tpu-v6e", ici_dims=2, chips_per_host=8, max_chips=256,
                 standalone_counts=(1, 2, 4), host_dims=(2, 4)),
    )
}


def is_tpu_model(model: str) -> bool:
    return model in TPU_MODELS


@dataclass(frozen=True)
class SliceShape:
    model: str
    dims: Tuple[int, ...]  # e.g. (2, 2, 4)
    num_chips: int
    num_hosts: int
    chips_per_host: int

    @property
    def topology(self) -> str:
        return "x".join(str(d) for d in self.dims)

    def worker_chip_indices(self, worker_id: int) -> List[int]:
        """Chip indices (slice-local) owned by one host/worker."""
        start = worker_id * self.chips_per_host
        return list(range(start, min(start + self.chips_per_host, self.num_chips)))


def _is_pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


def _parse_dims(topology: str) -> Tuple[int, ...]:
    try:
        dims = tuple(int(p) for p in topology.lower().split("x"))
    except ValueError:
        raise TopologyError(f"unparseable topology {topology!r}") from None
    if not dims or any(d < 1 for d in dims):
        raise TopologyError(f"invalid topology {topology!r}")
    return dims


def _candidate_shapes(model: TpuModel, count: int) -> List[Tuple[int, ...]]:
    """All valid dim-tuples (sorted ascending) for `count` chips."""
    if count in model.standalone_counts:
        # Standalone sub-host shape: a simple line, no torus constraint.
        return [(count,) if model.ici_dims == 2 else (1, 1, count)]
    if count % model.chips_per_host != 0:
        return []
    if count == model.chips_per_host:
        # One full host: the slice shape IS the host tray shape.
        return [tuple(sorted(model.host_dims))]
    out = []
    if model.ici_dims == 3:
        for x in _pow2_divisors(count):
            for y in _pow2_divisors(count // x):
                z = count // (x * y)
                if x <= y <= z and _is_pow2(z) and x >= 2:
                    out.append((x, y, z))
    else:
        for x in _pow2_divisors(count):
            y = count // x
            if x <= y and _is_pow2(y) and x >= 2:
                out.append((x, y))
    return out


def _pow2_divisors(n: int) -> List[int]:
    return [d for d in (2 ** i for i in range(n.bit_length())) if n % d == 0]


def _compactness(dims: Tuple[int, ...]) -> float:
    # Lower is better: max/min aspect ratio; ties broken by perimeter.
    return max(dims) / min(dims) + 1e-3 * sum(dims)


def solve_slice(model_name: str, count: int, topology: str = "") -> SliceShape:
    """Solve `count` chips of `model_name` into a valid slice shape.

    An explicit ``topology`` (e.g. "2x2x4") pins the shape after validation;
    otherwise the most compact valid shape is chosen.
    """
    model = TPU_MODELS.get(model_name)
    if model is None:
        raise TopologyError(
            f"unknown TPU model {model_name!r}; known: {sorted(TPU_MODELS)}"
        )
    if count < 1:
        raise TopologyError("chip count must be >= 1")
    if count > model.max_chips:
        raise TopologyError(
            f"{model_name} supports at most {model.max_chips} chips, requested {count}"
        )

    candidates = _candidate_shapes(model, count)
    if not candidates:
        valid = sorted(
            set(model.standalone_counts)
            | {c for c in range(model.chips_per_host, min(count * 2, model.max_chips) + 1, model.chips_per_host)
               if _candidate_shapes(model, c)}
        )
        raise TopologyError(
            f"{count} chips of {model_name} cannot form a slice;"
            f" nearby valid counts: {valid[:12]}"
        )

    if topology:
        dims = _parse_dims(topology)
        want = 1
        for d in dims:
            want *= d
        if want != count:
            raise TopologyError(
                f"topology {topology!r} has {want} chips but size is {count}"
            )
        if tuple(sorted(dims)) not in {tuple(sorted(c)) for c in candidates}:
            raise TopologyError(
                f"topology {topology!r} is not a valid {model_name} slice shape;"
                f" valid: {['x'.join(map(str, c)) for c in candidates]}"
            )
    else:
        dims = min(candidates, key=_compactness)

    num_hosts = max(1, count // model.chips_per_host)
    return SliceShape(
        model=model_name,
        dims=tuple(dims),
        num_chips=count,
        num_hosts=num_hosts,
        chips_per_host=min(count, model.chips_per_host),
    )
