"""Workload-side integration: coordinate consumption + slice acceptance.

The closing of the loop: the operator composes a slice and injects TPU_*
coordinates (admission.coordinates); this package is what a JAX workload
calls to consume them — bootstrap jax.distributed from the injected env,
build the mesh, and qualify the slice (allreduce bandwidth + a real sharded
train step) before the job trusts it.
"""

from tpu_composer.workload.coords import SliceCoords, bootstrap_distributed
from tpu_composer.workload.acceptance import qualify_slice

__all__ = ["SliceCoords", "bootstrap_distributed", "qualify_slice"]
