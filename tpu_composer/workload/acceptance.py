"""Slice qualification — prove a freshly composed slice actually works.

The reference's notion of device health is `nvidia-smi` answering and the
fabric reporting OK (composableresource_controller.go:317-330). For a TPU
slice that is not enough: the ICI mesh must move bytes and the MXU must hit
rate. ``qualify_slice`` runs the two north-star probes (BASELINE.md):

1. allreduce busbw over the mesh (ICI health + topology sanity);
2. a real sharded train step of the flagship model (MXU + memory system +
   collective overlap), returning step time and achieved TFLOP/s.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from tpu_composer.models.transformer import ModelConfig
from tpu_composer.parallel.collectives import allreduce_bandwidth_gbps
from tpu_composer.parallel.mesh import make_mesh, solve_mesh_axes
from tpu_composer.parallel.train import TrainConfig, make_train_state, make_train_step


def _model_flops_per_token(c: ModelConfig) -> float:
    """~6 * params matmul FLOPs per token for fwd+bwd (standard estimate;
    excludes the attention S*d term, so derived MFU is slightly
    conservative at long seq)."""
    per_layer = (
        3 * c.d_model * c.n_heads * c.head_dim  # qkv
        + c.n_heads * c.head_dim * c.d_model  # out proj
        + 3 * c.d_model * c.d_ff  # swiglu
    )
    params = c.n_layers * per_layer + c.vocab_size * c.d_model
    return 6.0 * params


# Per-chip dense bf16 peaks (public spec sheets), matched against
# device_kind prefixes. BASELINE.md's north star is an explicit MFU line:
# achieved TFLOPS / (n_devices * peak).
_BF16_PEAK_TFLOPS = (
    ("TPU v5 lite", 197.0),  # v5e
    ("TPU v5e", 197.0),
    ("TPU v5p", 459.0),
    ("TPU v5", 459.0),  # after v5e/v5p prefixes: bare v5 reports as p
    ("TPU v4 lite", 137.0),
    ("TPU v4", 275.0),
    ("TPU v6 lite", 918.0),  # Trillium / v6e
    ("TPU v6e", 918.0),
)


def _bf16_peak_tflops() -> Optional[float]:
    try:
        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 - no backend, no peak
        return None
    for prefix, peak in _BF16_PEAK_TFLOPS:
        if kind.startswith(prefix):
            return peak
    return None


def qualify_slice(
    mesh: Optional[Mesh] = None,
    batch: int = 8,
    seq: int = 512,
    model_config: Optional[ModelConfig] = None,
    allreduce_mb: float = 64.0,
    steps: int = 5,
) -> Dict[str, float]:
    if mesh is None:
        mesh = make_mesh(solve_mesh_axes(len(jax.devices())))
    mc = model_config or ModelConfig(
        vocab_size=8192, d_model=512, n_layers=4, n_heads=8, d_ff=1408, max_seq=seq,
        # Flash is the Mosaic fast path; in interpret mode (CPU smoke runs)
        # it would be a Python-looped slow path, so qualify with the fused
        # XLA reference there instead.
        attn_impl="flash" if jax.default_backend() == "tpu" else "reference",
    )

    results: Dict[str, float] = {
        "n_devices": float(int(np.prod(mesh.devices.shape))),
        "allreduce_gbps": allreduce_bandwidth_gbps(mesh, size_mb=allreduce_mb),
    }

    def build(cfg):
        tc = TrainConfig(model=cfg)
        st = make_train_state(tc, jax.random.key(0), mesh)
        fn, sharding = make_train_step(tc, mesh)
        toks = jax.device_put(
            jax.random.randint(jax.random.key(1), (batch, seq), 0, cfg.vocab_size),
            sharding,
        )
        st, met = fn(st, toks)  # compile + first step
        jax.block_until_ready(met)
        return st, fn, toks, met

    try:
        state, step_fn, tokens, metrics = build(mc)
    except Exception:
        # The Pallas kernels are the fast path, never the only path: a
        # Mosaic lowering regression must degrade the number, not the
        # bench. The traceback is logged AND the result is tagged
        # (attn_fallback=1) so bench consumers see a degraded run without
        # log scraping — a silent fallback would bury the regression behind
        # plausible-looking reference numbers.
        if mc.attn_impl == "reference":
            raise
        logging.getLogger("qualify_slice").warning(
            "attn_impl=%s failed to build; falling back to reference",
            mc.attn_impl, exc_info=True,
        )
        mc = dataclasses.replace(mc, attn_impl="reference")
        results["attn_fallback"] = 1.0
        state, step_fn, tokens, metrics = build(mc)
    results["attn_impl"] = mc.attn_impl  # type: ignore[assignment]
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, tokens)
    jax.block_until_ready(metrics)
    dt = (time.perf_counter() - t0) / steps

    tokens_per_step = batch * seq
    results["train_step_ms"] = dt * 1e3
    results["train_loss"] = float(metrics["loss"])
    results["tokens_per_s"] = tokens_per_step / dt
    results["tflops"] = _model_flops_per_token(mc) * tokens_per_step / dt / 1e12
    peak = _bf16_peak_tflops()
    if peak:
        results["mfu"] = results["tflops"] / (results["n_devices"] * peak)
    return results
