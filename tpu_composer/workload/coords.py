"""Consume the injected TPU_* coordinates.

The contract is exactly what admission.coordinates.slice_env writes (and the
CDI specs carry): a workload process on a composed slice reads its identity
from env, initializes jax.distributed for multi-host, and gets a mesh over
the slice's devices. The reference never had this layer — its workloads were
opaque pods; ours closes the loop to JAX.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

log = logging.getLogger("workload.coords")

DEFAULT_COORDINATOR_PORT = 8476


@dataclass(frozen=True)
class SliceCoords:
    worker_id: int
    worker_hostnames: List[str]
    chips_per_host: int
    topology: str
    slice_name: str
    model: str = ""

    @property
    def num_workers(self) -> int:
        return max(1, len(self.worker_hostnames))

    @property
    def num_chips(self) -> int:
        return self.chips_per_host * self.num_workers

    @property
    def coordinator_address(self) -> str:
        host = self.worker_hostnames[0] if self.worker_hostnames else "localhost"
        return f"{host}:{DEFAULT_COORDINATOR_PORT}"

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None) -> "SliceCoords":
        e = os.environ if env is None else env
        hostnames = [h for h in e.get("TPU_WORKER_HOSTNAMES", "").split(",") if h]
        # TPU_CHIPS_PER_HOST_BOUNDS is a per-dimension grid ("2,2,1", the
        # libtpu convention); the chip count is its product.
        bounds = e.get("TPU_CHIPS_PER_HOST_BOUNDS", "")
        chips = 0
        if bounds:
            chips = 1
            for p in bounds.split(","):
                chips *= int(p or 1)
        return cls(
            worker_id=int(e.get("TPU_WORKER_ID", "0")),
            worker_hostnames=hostnames,
            chips_per_host=chips,
            topology=e.get("TPU_TOPOLOGY", ""),
            slice_name=e.get("TPU_SLICE_NAME", ""),
            model=e.get("TPU_ACCELERATOR_MODEL", ""),
        )


def bootstrap_distributed(
    coords: Optional[SliceCoords] = None,
    env: Optional[Dict[str, str]] = None,
) -> SliceCoords:
    """Initialize jax.distributed from injected coordinates (multi-host
    slices only; single-host is a no-op). Idempotent. Returns the coords.

    Worker 0's host is the coordinator — the same convention libtpu's
    megascale setup uses, so the injected hostname list is sufficient.
    """
    coords = coords or SliceCoords.from_env(env)
    if coords.num_workers <= 1:
        return coords
    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=coords.coordinator_address,
            num_processes=coords.num_workers,
            process_id=coords.worker_id,
        )
        log.info(
            "jax.distributed up: worker %d/%d via %s",
            coords.worker_id, coords.num_workers, coords.coordinator_address,
        )
    except RuntimeError as e:
        # Already initialized (restart inside the same process) is fine.
        if "already" not in str(e).lower():
            raise
    return coords
