"""Collective-traffic accounting from compiled XLA programs.

The single tunneled chip can never measure multi-chip allreduce GB/s, and
the 8-device CPU mesh measures memcpy, not ICI. What CAN be extracted
without hardware — and is exact, not modeled — is the collective schedule
XLA actually compiled for the target slice: which collectives run per train
step, over which mesh axis, moving how many bytes. This module parses the
post-optimization HLO of an AOT-compiled program (the same v5e pipeline as
tests/test_multichip_aot_tpu.py) and attributes every collective instance
to the mesh axis its replica groups span — the best available proxy for
the north-star "JAX allreduce GB/s on composed slice" until multi-chip
hardware exists (VERDICT r4 missing #4 / ask #4).

Caveats, stated so the numbers cannot overclaim:
- Counts are static HLO instances. The dense/MoE paths unroll layers, so
  static count == per-step executions; the pipeline path scans
  microbatches, where an in-loop instance executes once per microbatch.
- ``collective-permute`` (the ring-attention hop) reports bytes per hop;
  a ring of size N executes N-1 hops per ring pass.

Reference contrast: the reference has no data-plane collectives at all
(SURVEY.md §5 — its "communication backend" is fabric REST + pod-exec).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
    "s32": 4, "u32": 4, "s64": 8, "u64": 8, "f8e4m3fn": 1, "f8e5m2": 1,
    "bf16": 2, "f16": 2, "f32": 4, "f64": 8, "c64": 8, "c128": 16,
}

# One HLO shape like ``bf16[2,64,128]{2,1,0}`` or a scalar ``f32[]``.
_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")

_COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
    "collective-permute",
)


def _shape_bytes(shape_str: str) -> int:
    """Total bytes of one shape or a (tuple, of, shapes) string."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_str):
        if dtype not in _DTYPE_BYTES:
            continue
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * _DTYPE_BYTES[dtype]
    return total


def _axis_partitions(mesh_axes: Dict[str, int],
                     device_ids: Sequence[int]) -> Dict[str, frozenset]:
    """For every mesh axis (and every combination of axes), the partition of
    device ids into the groups a collective over that axis would use.

    ``device_ids``: the mesh's device-id array flattened in mesh order
    (row-major over the axes in dict order) — exactly how GSPMD numbers
    participants in replica_groups for SPMD programs."""
    names = list(mesh_axes)
    sizes = [mesh_axes[n] for n in names]
    grid = np.asarray(list(device_ids)).reshape(sizes)
    out: Dict[str, frozenset] = {}
    # Singles first, then pairs, etc. — first match wins in the caller, so
    # a group set that IS a single axis is labeled as such even when it
    # also equals some combined-axes partition (e.g. size-1 axes present).
    from itertools import combinations

    for r in range(1, len(names) + 1):
        for combo in combinations(range(len(names)), r):
            label = "+".join(names[i] for i in combo)
            moved = np.moveaxis(grid, combo, range(len(combo)))
            flat = moved.reshape(
                int(np.prod([sizes[i] for i in combo])), -1
            )
            groups = frozenset(
                frozenset(int(x) for x in flat[:, j])
                for j in range(flat.shape[1])
            )
            out.setdefault(label, groups)
    return out


def _parse_groups(line: str) -> Optional[frozenset]:
    m = re.search(r"replica_groups=\{(\{[0-9,{}\s]*\})\}", line)
    if not m:
        # Newer HLO may print replica_groups=[2,4]<=[8] (iota form).
        m2 = re.search(
            r"replica_groups=\[(\d+),(\d+)\]<=\[(\d+)\]", line
        )
        if m2:
            rows, cols, total = (int(x) for x in m2.groups())
            ids = np.arange(total).reshape(rows, cols)
            return frozenset(
                frozenset(int(x) for x in row) for row in ids
            )
        m3 = re.search(
            r"replica_groups=\[(\d+),(\d+)\]<=\[([0-9,]+)\]T\(([0-9,]+)\)",
            line,
        )
        if m3:
            rows, cols = int(m3.group(1)), int(m3.group(2))
            dims = [int(x) for x in m3.group(3).split(",")]
            perm = [int(x) for x in m3.group(4).split(",")]
            ids = np.arange(int(np.prod(dims))).reshape(dims)
            ids = np.transpose(ids, perm).reshape(rows, cols)
            return frozenset(
                frozenset(int(x) for x in row) for row in ids
            )
        return None
    inner = m.group(1)
    return frozenset(
        frozenset(int(x) for x in grp.split(",") if x.strip())
        for grp in re.findall(r"\{([0-9,\s]*)\}", inner)
    )


def _parse_permute_pairs(line: str) -> Optional[List[Tuple[int, int]]]:
    m = re.search(r"source_target_pairs=\{([0-9,{}\s]*)\}", line)
    if not m:
        return None
    return [
        (int(a), int(b))
        for a, b in re.findall(r"\{(\d+),(\d+)\}", m.group(1))
    ]


def _permute_axis(pairs: List[Tuple[int, int]],
                  partitions: Dict[str, frozenset]) -> str:
    """A ppermute ring stays inside one axis's groups: find the axis whose
    partition contains every {src,dst} pair within a single group."""
    for label, groups in sorted(partitions.items(),
                                key=lambda kv: kv[0].count("+")):
        bygroup = {d: g for g in groups for d in g}
        if all(
            dst in bygroup.get(src, frozenset()) for src, dst in pairs
        ):
            return label
    return "unmapped"


def collective_summary(
    hlo_text: str,
    mesh_axes: Dict[str, int],
    device_ids: Optional[Sequence[int]] = None,
) -> Dict[str, Any]:
    """Summarize the collective ops in post-optimization HLO text.

    Returns {"ops": [...], "per_axis_bytes": {...}, "total_bytes": N,
    "op_counts": {...}} where each op record carries kind, axis label,
    group size, static instance count and bytes per instance."""
    if device_ids is None:
        device_ids = list(range(int(np.prod(list(mesh_axes.values())))))
    partitions = _axis_partitions(mesh_axes, device_ids)

    # The op is located by name, not by parsing the result shape first:
    # tuple results (gradient-bucket all-reduces) and TPU layout
    # annotations like {1,0:T(8,128)(2,1)S(1)} embed parentheses that
    # defeat any "match the shape then the op" regex. ``-done`` halves of
    # async pairs never match (the op name is followed by "-done(", not
    # "(" or "-start("), so each collective is counted exactly once.
    op_re = re.compile(
        r"=\s(.*?)\s(" + "|".join(_COLLECTIVE_OPS) + r")(?:-start)?\("
    )
    per_key: Dict[Tuple[str, str, int], Dict[str, Any]] = {}
    for raw_line in hlo_text.splitlines():
        line = raw_line.strip()
        if not line.startswith(("%", "ROOT")):
            continue
        m = op_re.search(line)
        if not m:
            continue
        kind = m.group(2)
        nbytes = _shape_bytes(m.group(1))
        if kind == "collective-permute":
            pairs = _parse_permute_pairs(line)
            axis = _permute_axis(pairs, partitions) if pairs else "unmapped"
            gsize = 1
            for part in axis.split("+"):
                gsize *= mesh_axes.get(part, 1)
            if axis == "unmapped":
                gsize = 0
        else:
            groups = _parse_groups(line)
            axis, gsize = "unmapped", 0
            if groups:
                gsize = max((len(g) for g in groups), default=0)
                for label, part in sorted(
                    partitions.items(), key=lambda kv: kv[0].count("+")
                ):
                    if groups == part:
                        axis = label
                        break
                else:
                    # Sub-axis or cross-axis grouping that is not a full
                    # partition match (e.g. groups within one dp shard):
                    # label by the smallest axis-combination whose groups
                    # are supersets of these groups.
                    for label, part in sorted(
                        partitions.items(),
                        key=lambda kv: kv[0].count("+"),
                    ):
                        bygroup = {d: g for g in part for d in g}
                        if all(
                            g <= bygroup.get(next(iter(g)), frozenset())
                            for g in groups
                        ):
                            axis = f"within-{label}"
                            break
        key = (kind, axis, nbytes)
        rec = per_key.setdefault(
            key,
            {"op": kind, "axis": axis, "group_size": gsize,
             "bytes_per_instance": nbytes, "instances": 0},
        )
        rec["instances"] += 1

    ops = sorted(
        per_key.values(),
        key=lambda r: -r["bytes_per_instance"] * r["instances"],
    )
    per_axis: Dict[str, int] = {}
    op_counts: Dict[str, int] = {}
    for r in ops:
        total = r["bytes_per_instance"] * r["instances"]
        per_axis[r["axis"]] = per_axis.get(r["axis"], 0) + total
        op_counts[r["op"]] = op_counts.get(r["op"], 0) + r["instances"]
    return {
        "mesh_axes": dict(mesh_axes),
        "ops": ops,
        "per_axis_bytes": per_axis,
        "op_counts": op_counts,
        "total_bytes": sum(per_axis.values()),
    }


def summarize_compiled(compiled, mesh_axes: Dict[str, int],
                       mesh) -> Dict[str, Any]:
    """One compiled-executable entry point shared by every producer of
    collective evidence (bench AOT child, dryrun_multichip, `make
    collectives`): the HLO-text / axes-dict / device-id-order convention
    lives HERE, so the artifacts cannot silently diverge."""
    return collective_summary(
        compiled.as_text(), dict(mesh_axes),
        [d.id for d in np.asarray(mesh.devices).flatten()],
    )


def _compile_and_summarize() -> Dict[str, Any]:
    """AOT-compile the 8-chip dense (zigzag sp) and 16-chip MoE (ep) train
    steps for real v5e topologies and summarize their collectives — the
    generator behind bench_artifacts/collectives_v5e.json (cited by
    docs/PERF.md) and the bench AOT stage's ``collectives`` fields."""
    import os

    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.experimental import topologies
    from jax.sharding import Mesh

    from tpu_composer.models import ModelConfig, MoEConfig
    from tpu_composer.parallel import (
        TrainConfig,
        abstract_train_state,
        make_train_step,
        solve_mesh_axes,
    )

    common = dict(vocab_size=512, d_model=128, n_layers=2, n_heads=4,
                  d_ff=256, max_seq=64, dtype=jnp.bfloat16)

    from tpu_composer.workload.libtpu_serial import libtpu_serialized

    def run(topo, axes, tc, batch):
        with libtpu_serialized():
            devs = topologies.get_topology_desc(topo, "tpu").devices
        mesh = Mesh(
            np.array(devs).reshape([axes[a] for a in axes]), tuple(axes)
        )
        state = abstract_train_state(tc, mesh)
        step_fn, bs = make_train_step(tc, mesh)
        tokens = jax.ShapeDtypeStruct((batch, 64), jnp.int32, sharding=bs)
        compiled = step_fn.lower(state, tokens).compile()
        return summarize_compiled(compiled, axes, mesh)

    axes8 = solve_mesh_axes(8, sp=2, tp=2)
    dense = run(
        "v5e:2x4", axes8,
        TrainConfig(model=ModelConfig(**common), sp_impl="zigzag"),
        2 * axes8["dp"],
    )
    axes16 = solve_mesh_axes(16, ep=2, sp=2, tp=2)
    moe = run(
        "v5e:4x4", axes16,
        TrainConfig(model=MoEConfig(n_experts=4, top_k=2,
                                    capacity_factor=2.0, moe_period=2,
                                    **common)),
        2 * axes16["dp"] * axes16["ep"],
    )
    return {
        "note": (
            "Per-train-step collective traffic of the compiled XLA programs "
            "for real v5e topologies (static HLO instances; layers are "
            "unrolled so counts are per-step). Regenerate: make collectives"
        ),
        "dense_zigzag_v5e_2x4": dense,
        "moe_ep_v5e_4x4": moe,
    }


if __name__ == "__main__":
    import json
    import os
    import sys

    out = _compile_and_summarize()
    dest = sys.argv[1] if len(sys.argv) > 1 else os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))),
        "bench_artifacts", "collectives_v5e.json",
    )
    os.makedirs(os.path.dirname(dest), exist_ok=True)
    with open(dest, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {dest}")
