"""Cross-process serialization for libtpu topology access.

libtpu guards itself with /tmp/libtpu_lockfile and ABORTS when two
processes touch the TPU topology machinery concurrently (observed abort
point: ``topologies.get_topology_desc``). Every device-less AOT user —
the pytest-xdist workers' AOT suites, the bench/relay-watcher probe
child, ``make collectives`` — must take this flock around topology init
so they queue instead of racing. One-sided locking is worthless: a probe
child initializing libtpu while a test worker holds the lock still
aborts one of them (ADVICE r5 finding).
"""

from __future__ import annotations

import contextlib
import fcntl
import os
import tempfile


@contextlib.contextmanager
def libtpu_serialized():
    path = os.path.join(
        tempfile.gettempdir(), f"tpuc_libtpu_serial_{os.getuid()}.flock"
    )
    with open(path, "w") as f:
        fcntl.flock(f, fcntl.LOCK_EX)
        try:
            yield
        finally:
            fcntl.flock(f, fcntl.LOCK_UN)
