"""Staged accelerator probe — produce numbers *or* a named-stage diagnosis.

Round 1's bench ran the whole slice qualification in one subprocess under one
420 s timeout and returned nothing when the device tunnel hung — so the bench
carried zero accelerator evidence (VERDICT.md "What's weak" #1). This module
splits the probe into ordered stages, each reported the moment it completes:

  devnodes       device-node / env / pool-endpoint preflight (pure os, in-process)
  backend_init   ``jax.devices()`` — PJRT plugin + tunnel handshake
  matmul         one tiny jitted bf16 matmul (compiler + executor round trip)
  flash_attn     Pallas flash fwd+bwd vs the XLA reference (numerics on-chip)
  qualify        full ``qualify_slice`` (allreduce busbw + train-step TFLOPS)
  qualify_large  MXU-sized bf16 pass (TPU only; degrades to an error record)

Stages after ``devnodes`` run in ONE subprocess that prints a
``STAGE_RESULT <json>`` line per completed stage; the parent tails the pipe
with a per-stage deadline. A hang therefore costs only the hanging stage's
timeout and still yields every earlier stage's numbers plus the name of the
stage that died and the subprocess's stderr tail.

Reference analog: the reference's only device health probe is `nvidia-smi`
answering over pod-exec (/root/reference/internal/utils/gpus.go:207-239);
it has no staged diagnosis at all — a hang there surfaces as a generic
reconcile timeout.
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

# Each stage gets its own deadline, measured from the previous stage's
# completion. backend_init dominates: a cold PJRT tunnel handshake plus the
# first compile is the documented slow path. r02's probe died here at a 240 s
# budget with no stack; VERDICT r3 ask #1 raised it back to >=420 s with a
# retry and in-child faulthandler dumps.
STAGE_TIMEOUTS_S: Dict[str, float] = {
    "backend_init": 480.0,
    "matmul": 120.0,
    # flash_attn sweeps 4 configs (seq 1k-8k, MHA/GQA/MQA), each compiling
    # up to 4 chained timing scans plus numerics jits on the short ones,
    # through the remote-compile tunnel; the persistent compilation cache
    # makes repeat probes cheap but the first live run needs headroom.
    "flash_attn": 900.0,
    "qualify": 420.0,
    "qualify_large": 420.0,
    # decode now compiles ~8 programs on a cold cache (batch-8 + batch-1
    # generate, prefills, draft roll, verify chunks) through the
    # remote-compile tunnel — same headroom rationale as flash_attn.
    "decode": 900.0,
}

_CHILD = r"""
import faulthandler, json, os, sys, time

# Arm the hang reporter BEFORE import jax: if any stage wedges (PJRT tunnel
# handshake being the repeat offender — BENCH_r01/r02 both died in
# backend_init with an empty stderr), the exact blocking stack of every
# thread is dumped to stderr ~10 s before the parent's deadline, then the
# child exits so the parent gets a clean failed-stage record instead of a
# kill with no evidence.
_budget = float(os.environ.get("TPUC_PROBE_STAGE_BUDGET_S", "480"))
faulthandler.dump_traceback_later(max(_budget - 10.0, 5.0), exit=True)

def emit(stage, t0, **kv):
    kv["stage"] = stage
    kv["seconds"] = round(time.time() - t0, 2)
    print("STAGE_RESULT " + json.dumps(kv), flush=True)

def rearm(budget):
    faulthandler.cancel_dump_traceback_later()
    faulthandler.dump_traceback_later(max(budget - 10.0, 5.0), exit=True)

_timeouts = json.loads(os.environ.get("TPUC_PROBE_TIMEOUTS", "{}"))

t0 = time.time()
import jax
# The image's sitecustomize registers the accelerator platform at interpreter
# start and the env var alone is read too late to override it — honor an
# explicit JAX_PLATFORMS through the live config (same dance as
# tests/conftest.py), so CPU smoke runs of this probe exercise every stage.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
devs = jax.devices()
try:
    version = jax.extend.backend.get_backend().platform_version
except Exception:
    version = "unknown"
emit("backend_init", t0, backend=jax.default_backend(),
     n_devices=len(devs), device_kind=devs[0].device_kind,
     platform_version=version)

rearm(_timeouts.get("matmul", 120.0))
t0 = time.time()
import jax.numpy as jnp
x = jnp.ones((512, 512), jnp.bfloat16)
y = jax.jit(lambda a: a @ a)(x)
y.block_until_ready()
emit("matmul", t0, ok=True, result_dtype=str(y.dtype))

rearm(_timeouts.get("flash_attn", 900.0))
t0 = time.time()
try:
    from tpu_composer.workload.probe import flash_sweep_on_chip
    emit("flash_attn", t0, **flash_sweep_on_chip())
except Exception as e:  # noqa: BLE001 - diagnosis, not control flow
    emit("flash_attn", t0, error=f"{type(e).__name__}: {e}")

rearm(_timeouts.get("qualify", 420.0))
t0 = time.time()
from tpu_composer.workload.acceptance import qualify_slice
results = qualify_slice(batch=4, seq=512, allreduce_mb=16.0, steps=5)
results["backend"] = jax.default_backend()
emit("qualify", t0, **results)

# MXU-sized pass, TPU only: the tiny config above validates the stack but
# utilizes a few percent of the MXU; the headline TFLOPS number needs
# matmuls big enough to tile the systolic array (d_model 2048, ffn 8192,
# bf16, seq 2048 — ~200M params, ~20 TFLOP/step).
rearm(_timeouts.get("qualify_large", 420.0))
t0 = time.time()
try:
    if jax.default_backend() == "tpu":
        import jax.numpy as jnp
        from tpu_composer.models.transformer import ModelConfig
        big = ModelConfig(vocab_size=32768, d_model=2048, n_layers=4,
                          n_heads=16, d_ff=8192, max_seq=2048,
                          dtype=jnp.bfloat16, attn_impl="flash")
        results = qualify_slice(batch=8, seq=2048, model_config=big,
                                allreduce_mb=64.0, steps=3)
        results["backend"] = jax.default_backend()
        emit("qualify_large", t0, **results)
    else:
        emit("qualify_large", t0,
             skipped="MXU-sized pass is meaningful on tpu only")
except Exception as e:  # noqa: BLE001 - enhancement pass degrades, never fails
    # (e.g. OOM on a small-HBM chip): the five core stages already carry
    # their evidence; record the error instead of failing the probe.
    emit("qualify_large", t0, error=f"{type(e).__name__}: {e}")

# Serving throughput, TPU only: KV-cached greedy decode tokens/s for the
# bf16 baseline vs the fully-quantized path (int8 weights + int8 cache).
rearm(_timeouts.get("decode", 900.0))
t0 = time.time()
try:
    if jax.default_backend() == "tpu":
        from tpu_composer.workload.probe import decode_throughput_on_chip
        emit("decode", t0, **decode_throughput_on_chip())
    else:
        emit("decode", t0, skipped="decode bench is meaningful on tpu only")
except Exception as e:  # noqa: BLE001 - enhancement pass degrades, never fails
    emit("decode", t0, error=f"{type(e).__name__}: {e}")
faulthandler.cancel_dump_traceback_later()
"""


def probe_pool_endpoints(timeout_s: float = 1.0) -> List[Dict[str, Any]]:
    """TCP-preflight the device-pool/tunnel endpoints the PJRT plugin will
    dial (VERDICT r3 ask #1): when backend_init hangs, the first question is
    whether the pool service behind ``PALLAS_AXON_POOL_IPS`` /
    ``AXON_POOL_SVC_OVERRIDE`` is even accepting connections. Entries may be
    ``host`` or ``host:port``; bare hosts are scanned on the candidate ports
    the local relay is known to use. Pure sockets, bounded by timeout_s per
    endpoint — cannot wedge the probe."""
    import socket

    candidates: List[Tuple[str, int]] = []
    seen = set()
    port_guesses = (8082, 8083, 8087, 8092)
    for var in ("PALLAS_AXON_POOL_IPS", "AXON_POOL_SVC_OVERRIDE"):
        for entry in os.environ.get(var, "").split(","):
            entry = entry.strip()
            if not entry:
                continue
            host, _, port = entry.rpartition(":")
            if host and port.isdigit():
                pairs = [(host, int(port))]
            else:
                pairs = [(entry, p) for p in port_guesses]
            for pair in pairs:
                if pair not in seen:
                    seen.add(pair)
                    candidates.append(pair)
    out: List[Dict[str, Any]] = []
    for host, port in candidates:
        rec: Dict[str, Any] = {"endpoint": f"{host}:{port}"}
        t0 = time.perf_counter()
        try:
            with socket.create_connection((host, port), timeout=timeout_s):
                rec["reachable"] = True
                rec["connect_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
        except OSError as e:
            rec["reachable"] = False
            rec["error"] = f"{type(e).__name__}: {e}"
        out.append(rec)
    return out


def loopback_relay_mode(env: Optional[Dict[str, str]] = None) -> bool:
    """True when AXON_LOOPBACK_RELAY requests in-process relay mode.
    Conventional disable spellings ("0", "false", "no", "off", empty) are
    OFF — plain string truthiness would read the explicit opt-out
    AXON_LOOPBACK_RELAY=0 as loopback mode and disarm the tunnel-down
    clamp on a box whose relay really is a dead TCP service."""
    value = (env if env is not None else os.environ).get(
        "AXON_LOOPBACK_RELAY", ""
    )
    return value.strip().lower() not in ("", "0", "false", "no", "off")


def probe_devnodes() -> Dict[str, Any]:
    """Stage a: what does the host itself say about accelerators?

    Pure filesystem/env enumeration — cannot hang, runs in-process. Mirrors
    what `native/tpunode.cc` scans, plus the libtpu/PJRT environment that
    decides which backend ``jax.devices()`` will try to bring up.
    """
    out: Dict[str, Any] = {
        "accel_nodes": sorted(glob.glob("/dev/accel*")),
        "vfio_nodes": sorted(glob.glob("/dev/vfio/*")),
        "libtpu_lockfile": os.path.exists("/tmp/libtpu_lockfile"),
        "env": {
            k: v
            for k, v in os.environ.items()
            if k.startswith(("JAX_", "TPU_", "XLA_", "PJRT_", "LIBTPU"))
            or "AXON" in k
        },
    }
    try:
        import importlib.util

        out["libtpu_installed"] = importlib.util.find_spec("libtpu") is not None
    except Exception:
        out["libtpu_installed"] = False
    out["pool_endpoints"] = probe_pool_endpoints()
    return out


def flash_attention_on_chip(
    batch: int = 2, heads: int = 8, seq: int = 1024, head_dim: int = 128,
    kv_heads: Optional[int] = None, check_numerics: bool = True,
) -> Dict[str, Any]:
    """Validate the Pallas flash kernels on the live backend (VERDICT #4).

    Runs fwd+bwd through both the flash path and the XLA einsum reference,
    asserts numerics, and times both at the given seq. Only meaningful on a
    TPU backend (Mosaic lowering); on CPU it reports the backend and skips.

    NOTE the argument order into the attention APIs is (B, S, H, D). The
    r3 probe built tensors as (batch, heads, seq, head_dim) — i.e. it
    benchmarked a degenerate seq-4, 1024-head attention where the flash
    grid collapses to thousands of (4 x 128) micro-kernels, and archived
    flash "losing" 0.91x/0.64x on a shape no model runs (VERDICT r3
    missing #3 traces to exactly this).
    """
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return {"skipped": f"backend is {jax.default_backend()}, not tpu"}

    from tpu_composer.ops.attention import flash_attention, mha_reference

    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    hk = kv_heads or heads
    q = jax.random.normal(kq, (batch, seq, heads, head_dim), jnp.bfloat16)
    k = jax.random.normal(kk, (batch, seq, hk, head_dim), jnp.bfloat16)
    v = jax.random.normal(kv, (batch, seq, hk, head_dim), jnp.bfloat16)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True).astype(jnp.float32).sum()

    def loss_ref(q, k, v):
        return mha_reference(q, k, v, causal=True).astype(jnp.float32).sum()

    f_fwd = jax.jit(lambda *a: flash_attention(*a, causal=True))
    r_fwd = jax.jit(lambda *a: mha_reference(*a, causal=True))
    f_grad = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))
    r_grad = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))

    fwd_err = bwd_err = None
    if check_numerics:
        of = f_fwd(q, k, v).block_until_ready()
        orf = r_fwd(q, k, v).block_until_ready()
        fwd_err = float(
            jnp.max(jnp.abs(of.astype(jnp.float32) - orf.astype(jnp.float32)))
        )
        gf = jax.block_until_ready(f_grad(q, k, v))
        gr = jax.block_until_ready(r_grad(q, k, v))
        bwd_err = max(
            float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
            for a, b in zip(gf, gr)
        )

    def bench(fn, *args, iters=8, reps=2, pick=lambda out: out):
        """Per-iteration device time via a lax.scan chain INSIDE one jit:
        iteration i+1's q depends on iteration i's output, so the device
        executes them back-to-back and one dispatch covers all of them.
        Per-call host timing (the previous approach) measured the axon
        tunnel's per-dispatch round trip, not the kernel — flash and
        reference came out within noise of the same number because both
        were gated on the same ~4 ms relay hop."""

        @jax.jit
        def chained(q, k, v):
            def body(c, _):
                out = pick(fn(c, k, v))
                return (c + 1e-6 * out).astype(c.dtype), ()

            c, _ = jax.lax.scan(body, q, None, length=iters)
            return c

        chained(*args).block_until_ready()  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            chained(*args).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        return best / iters * 1e3

    flash_ms = bench(f_fwd, q, k, v)
    ref_ms = bench(r_fwd, q, k, v)
    # Keep ALL three grads live in the carry: feeding only g[0] back would
    # let jaxpr DCE delete the dead dk/dv computation (the entire dkv
    # pallas_call on the flash path) and time half a backward. dk/dv are
    # head-summed so GQA shapes (KV < H) broadcast-add into the q carry.
    full = lambda g: g[0] + jnp.sum(g[1] + g[2], axis=2, keepdims=True)
    flash_bwd_ms = bench(f_grad, q, k, v, pick=full)
    ref_bwd_ms = bench(r_grad, q, k, v, pick=full)

    rec = {
        "seq": seq,
        "batch": batch,
        "heads": heads,
        "kv_heads": hk,
        "flash_fwd_ms": round(flash_ms, 3),
        "ref_fwd_ms": round(ref_ms, 3),
        "flash_bwd_ms": round(flash_bwd_ms, 3),
        "ref_bwd_ms": round(ref_bwd_ms, 3),
        "fwd_speedup": round(ref_ms / flash_ms, 2),
        "bwd_speedup": round(ref_bwd_ms / flash_bwd_ms, 2),
    }
    if check_numerics:
        # bf16 tolerance: sums over seq-length dot products accumulate
        # rounding error ~sqrt(S); anchor the envelope at the S=2048 bound
        # that has held on-chip and scale it for the longer configs the
        # sweep now also asserts (VERDICT r4 ask #6 — the first capture
        # must validate Mosaic at the length the headline speedup is
        # measured at, not only at seq <= 2048).
        tol = max(1.0, (seq / 2048.0) ** 0.5)
        rec["numerics_ok"] = fwd_err < 0.1 * tol and bwd_err < 0.5 * tol
        rec["fwd_max_err"] = round(fwd_err, 5)
        rec["bwd_max_err"] = round(bwd_err, 5)
    return rec


def flash_sweep_on_chip() -> Dict[str, Any]:
    """The flash kernel's report card across its operating envelope
    (VERDICT r3 ask #2): realistic head counts, seq 1k-8k, GQA/MQA fan-in.
    Numerics are asserted on-chip up to seq 4096 (the length the headline
    speedup is measured at); only the 8192 config is timing-only, its
    numerics pinned by the CPU-mesh tests (tests/test_flash_attention.py
    seq 2k-8k) and the v5e AOT compile gates. Headline fields summarize
    the long-seq regime (>= 4096) where the streaming kernel structurally
    beats the S^2-materializing reference."""
    import jax

    if jax.default_backend() != "tpu":
        return {"skipped": f"backend is {jax.default_backend()}, not tpu"}
    configs = [
        dict(batch=2, heads=8, seq=1024, check_numerics=True),
        dict(batch=2, heads=8, kv_heads=2, seq=2048, check_numerics=True),
        # 4096 asserts numerics ON-CHIP too (one extra fwd+grad pair per
        # side — cheap next to the timing reps): interpret-mode Pallas and
        # Mosaic have diverged on real hardware before, and the headline
        # long-seq speedup is measured at exactly this length.
        dict(batch=1, heads=8, kv_heads=2, seq=4096, check_numerics=True),
        dict(batch=1, heads=4, kv_heads=1, seq=8192, check_numerics=False),
    ]
    out: Dict[str, Any] = {"configs": []}
    for c in configs:
        try:
            rec = flash_attention_on_chip(**c)
        except Exception as e:  # noqa: BLE001 - keep earlier configs' data
            rec = {"seq": c["seq"], "error": f"{type(e).__name__}: {e}"}
        out["configs"].append(rec)
    longs = [r for r in out["configs"]
             if r.get("seq", 0) >= 4096 and "fwd_speedup" in r]
    if longs:
        # min(): the headline must surface a regression in ANY long config,
        # not let one winning config mask a losing one.
        out["fwd_speedup_long"] = min(r["fwd_speedup"] for r in longs)
        out["bwd_speedup_long"] = min(r["bwd_speedup"] for r in longs)
    nums = [r for r in out["configs"] if "numerics_ok" in r]
    if nums:
        out["numerics_ok"] = all(r["numerics_ok"] for r in nums)
    return out


def _best_wall_s(fn, reps: int = 3) -> float:
    """Warm (compile) once, then best-of-``reps`` wall seconds around
    ``fn().block_until_ready()`` — the one spelling of the device timing
    loop (the spec-decode block keeps its own interleaved variant on
    purpose: alternating the two programs under test cancels drift)."""
    fn().block_until_ready()
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn().block_until_ready()
        best = min(best, time.perf_counter() - t0)
    return best


def decode_throughput_on_chip(
    batch: int = 8,
    prompt_len: int = 128,
    new_tokens: int = 128,
) -> Dict[str, Any]:
    """KV-cached greedy decode tokens/s: bf16 baseline vs the fully
    quantized serving path (int8 weights + int8 KV cache). A mid-size
    config (d_model 1024, 8 layers, GQA 2) so weight streaming — the
    small-batch decode bound the quantization halves — dominates.

    generate() is one jitted program (prefill + lax.scan), so wall-clock
    around a single block_until_ready is honest device time (no per-token
    dispatch in the loop)."""
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return {"skipped": f"backend is {jax.default_backend()}, not tpu"}

    from tpu_composer.models.decode import generate
    from tpu_composer.models.quant import quantize_decode_params
    from tpu_composer.models.transformer import ModelConfig, init_params

    c = ModelConfig(vocab_size=32768, d_model=1024, n_layers=8, n_heads=16,
                    n_kv_heads=4, d_ff=4096,
                    max_seq=prompt_len + new_tokens, dtype=jnp.bfloat16)
    params = init_params(c, jax.random.key(0))
    qparams = quantize_decode_params(params)
    prompt = jax.random.randint(jax.random.key(1), (batch, prompt_len), 0,
                                c.vocab_size)

    out: Dict[str, Any] = {
        "batch": batch, "prompt_len": prompt_len, "new_tokens": new_tokens,
        "model": "d1024 L8 H16 kv4 ff4096 bf16",
    }
    for tag, p, quant in (("bf16", params, False),
                          ("int8_w_int8_kv", qparams, True)):
        fn = jax.jit(
            lambda pp, tk, q=quant: generate(
                pp, tk, c, max_new_tokens=new_tokens, kv_quant=q
            )
        )
        best = _best_wall_s(lambda: fn(p, prompt))
        out[f"{tag}_tokens_per_s"] = round(batch * new_tokens / best, 1)
        out[f"{tag}_ms_per_token"] = round(best / new_tokens * 1e3, 3)
    out["quant_speedup"] = round(
        out["int8_w_int8_kv_tokens_per_s"] / out["bf16_tokens_per_s"], 2
    )

    # Speculative decoding (int8 self-draft, batch 1 — its latency-mode
    # shape) vs plain greedy at batch 1: the serving stack's third lever,
    # so its on-chip claim carries hardware numbers like the other two.
    # Guarded so a failure here cannot discard the decode evidence already
    # in ``out`` (same keep-earlier-data pattern as the flash sweep), and
    # the quant numbers are emitted as a partial stage record FIRST — a
    # watchdog hard-exit mid-spec (which no try/except survives) must not
    # take minutes of already-measured evidence with it.
    print("STAGE_PARTIAL decode " + json.dumps(out), flush=True)
    try:
        from tpu_composer.models.speculative import speculative_generate

        gamma = 4
        p1 = prompt[:1]
        base = jax.jit(
            lambda pp, tk: generate(pp, tk, c, max_new_tokens=new_tokens)
        )

        def spec(pp, qp, tk):
            # No outer jit: the draft-accept loop is host-driven by design
            # (acceptance counts are data-dependent); its prefill/verify
            # chunks are jitted inside. That host round-trip is part of
            # the honest serving latency.
            return speculative_generate(
                pp, qp, tk, c, max_new_tokens=new_tokens, gamma=gamma,
                # The verify chunk can write up to gamma past the last
                # kept token; the cache must hold it.
                max_seq=prompt_len + new_tokens + gamma,
            )
        base(params, p1).block_until_ready()
        spec(params, qparams, p1).block_until_ready()
        best_b = best_s = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            base(params, p1).block_until_ready()
            best_b = min(best_b, time.perf_counter() - t0)
            t0 = time.perf_counter()
            spec(params, qparams, p1).block_until_ready()
            best_s = min(best_s, time.perf_counter() - t0)
        out["greedy_b1_tokens_per_s"] = round(new_tokens / best_b, 1)
        out["spec_b1_tokens_per_s"] = round(new_tokens / best_s, 1)
        out["spec_speedup"] = round(best_b / best_s, 2)
    except Exception as e:  # noqa: BLE001 - keep the quant evidence
        out["spec_error"] = f"{type(e).__name__}: {e}"

    # Paged KV cache (block pool + Mosaic block-walking kernel,
    # models/paged.py / ops/paged_attention.py): same greedy decode
    # through 128-token blocks, timed against the dense bf16 baseline
    # above. Emit-partial-first + isolated, like the spec block: paged
    # numbers are additive evidence and must never cost the earlier ones.
    print("STAGE_PARTIAL decode " + json.dumps(out), flush=True)
    try:
        from tpu_composer.models.paged import paged_generate

        blocks_needed = -(-(prompt_len + new_tokens) // 128) * batch
        paged = jax.jit(
            lambda pp, tk: paged_generate(
                pp, tk, c, max_new_tokens=new_tokens,
                num_blocks=blocks_needed, block_size=128,
                attn_impl="pallas",
            )
        )
        best_p = _best_wall_s(lambda: paged(params, prompt))
        out["paged_pallas_tokens_per_s"] = round(
            batch * new_tokens / best_p, 1
        )
        out["paged_vs_dense"] = round(
            out["paged_pallas_tokens_per_s"] / out["bf16_tokens_per_s"], 2
        )
    except Exception as e:  # noqa: BLE001 - keep the earlier evidence
        out["paged_error"] = f"{type(e).__name__}: {e}"
    return out


def staged_accelerator_probe(
    repo_root: Optional[str] = None,
    timeouts: Optional[Dict[str, float]] = None,
    retries: int = 1,
    fallbacks: bool = True,
) -> Dict[str, Any]:
    """Run all stages; return {stages: {...}, completed: [...], failed_stage,
    diagnosis}. Never raises, never hangs past the per-stage deadlines.

    backend_init gets ``retries`` extra attempts (fresh subprocess each time):
    the axon tunnel handshake has shown transient wedges, and one clean retry
    is cheaper than a lost round of hardware evidence. Each attempt's
    diagnosis is preserved under ``diagnosis.attempts``.

    ``fallbacks=False`` skips the CPU-stage rerun and the v5e AOT compile
    that normally follow a dead backend_init — for unit tests driving
    scripted children, where those minutes of real compilation would be
    spent on paths covered by their own suites (test_multichip_aot_tpu)."""
    timeouts = {**STAGE_TIMEOUTS_S, **(timeouts or {})}
    devnodes = probe_devnodes()
    order = ["backend_init", "matmul", "flash_attn", "qualify",
             "qualify_large", "decode"]

    env = dict(os.environ)
    root = repo_root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    env["TPUC_PROBE_STAGE_BUDGET_S"] = str(timeouts["backend_init"])
    env["TPUC_PROBE_TIMEOUTS"] = json.dumps(timeouts)
    # Verbose runtime/plugin logging: on the happy path it is merely chatty
    # stderr we never show; on a wedge it is the only record of how far the
    # PJRT handshake got. (TF_CPP covers XLA/PJRT C++, TPU_* covers libtpu.)
    import tempfile

    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(tempfile.gettempdir(),
                     f"tpuc_jax_cache_{os.getuid()}"),
    )
    env.setdefault("TF_CPP_MIN_LOG_LEVEL", "0")
    env.setdefault("TPU_STDERR_LOG_LEVEL", "0")
    env.setdefault("TPU_MIN_LOG_LEVEL", "0")

    # Tunnel-platform short circuit: when JAX_PLATFORMS points at a
    # tunneled backend (axon) whose pool/relay endpoints all refuse TCP,
    # the PJRT handshake does not fail — it blocks forever inside
    # xla_client.make_c_api_client (observed stack, r03). Burning the full
    # budget × retries on a relay that is provably down wastes the whole
    # bench window; one short attempt still captures the canonical hang
    # stack for the record.
    #
    # Exception (r05): under AXON_LOOPBACK_RELAY the relay runs in-process
    # with the PJRT plugin — there is no TCP listener at all, so an
    # all-refused preflight says nothing about the chip (observed r05: every
    # port refused while jax.devices() returned a live v5e). In loopback
    # mode backend_init itself, with its own deadline, is the only honest
    # reachability test — never clamp it.
    eps = devnodes.get("pool_endpoints", [])
    tunnel_down = bool(
        "axon" in env.get("JAX_PLATFORMS", "")
        and not loopback_relay_mode(env)
        and eps
        and not any(e.get("reachable") for e in eps)
    )
    if tunnel_down:
        timeouts = {**timeouts, "backend_init": min(timeouts["backend_init"], 60.0)}
        env["TPUC_PROBE_STAGE_BUDGET_S"] = str(timeouts["backend_init"])
        retries = 0
    elif "axon" in env.get("JAX_PLATFORMS", "") and loopback_relay_mode(env):
        # Loopback mode has no preflight signal at all: a healthy
        # in-process handshake completes in ~10 s, a wedged one blocks
        # forever, and TCP says nothing either way. Cap the handshake so a
        # dead relay costs minutes — not 480 s × (retries+1) — while
        # keeping ~15× headroom over a healthy init. Callers' explicit
        # smaller budgets still win (min).
        timeouts = {
            **timeouts,
            "backend_init": min(timeouts["backend_init"], 150.0),
        }
        env["TPUC_PROBE_STAGE_BUDGET_S"] = str(timeouts["backend_init"])

    failed_attempts: List[Dict[str, Any]] = []
    for attempt in range(retries + 1):
        stages, completed, failed_stage, stderr_tail = _drive_child(
            env, timeouts, order
        )
        if failed_stage != "backend_init" or attempt == retries:
            break
        failed_attempts.append(
            {"failed_stage": failed_stage, "stderr_tail": stderr_tail}
        )

    stages["devnodes"] = devnodes
    completed = ["devnodes"] + completed
    result: Dict[str, Any] = {"stages": stages, "completed": completed}
    if failed_stage:
        result["failed_stage"] = failed_stage
        result["diagnosis"] = {
            "timeout_s": timeouts.get(failed_stage),
            "stderr_tail": stderr_tail,
            "libtpu_lockfile": os.path.exists("/tmp/libtpu_lockfile"),
            "accel_nodes_present": bool(devnodes["accel_nodes"]),
            "pool_endpoints": probe_pool_endpoints(),
            "attempts": len(failed_attempts) + 1,
            "tunnel_down": tunnel_down,
        }
        if tunnel_down:
            result["diagnosis"]["blocked_call"] = (
                "xla_client.make_c_api_client (PJRT plugin handshake) — the "
                "tunnel relay behind PALLAS_AXON_POOL_IPS/AXON_POOL_SVC_"
                "OVERRIDE accepts no TCP connections; the C-API client init "
                "blocks indefinitely instead of erroring"
            )
        if failed_attempts:
            result["diagnosis"]["earlier_attempts"] = failed_attempts
        # The accelerator is unreachable, not the code: still produce
        # compute-stage numbers on the host backend so the round carries
        # *some* fresh measurements, explicitly tagged by their own
        # backend fields (qualify/backend_init each emit backend=cpu).
        if failed_stage == "backend_init" and fallbacks:
            fb_env = dict(env)
            fb_env["JAX_PLATFORMS"] = "cpu"
            # CPU backend init is seconds, not a tunnel handshake: 90 s is
            # plenty on real runs, but never MORE than the caller's own
            # backend_init budget (a test driving a scripted wedge would
            # otherwise burn 90 s re-wedging the fallback child).
            fb_timeouts = {
                **timeouts,
                "backend_init": min(90.0, timeouts["backend_init"]),
            }
            fb_env["TPUC_PROBE_STAGE_BUDGET_S"] = str(fb_timeouts["backend_init"])
            fb_stages, fb_completed, fb_failed, fb_tail = _drive_child(
                fb_env, fb_timeouts, order
            )
            fb: Dict[str, Any] = {"stages": fb_stages, "completed": fb_completed}
            if fb_failed:
                fb["failed_stage"] = fb_failed
                fb["stderr_tail"] = fb_tail
            result["cpu_fallback"] = fb
            # Compile-time hardware evidence that needs no hardware: run
            # the full XLA:TPU + Mosaic pipeline against a device-less v5e
            # topology (jax.experimental.topologies + installed libtpu) —
            # the flash grad kernels and the 8-chip sharded train step.
            # Proves the TPU programs this framework emits are compilable
            # for the target even when the tunnel relay is dead.
            result["tpu_aot_compile"] = aot_compile_probe(env)
    return result


_AOT_CHILD = r"""
import json, os, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
from jax.experimental import topologies
from jax.sharding import Mesh, SingleDeviceSharding

from tpu_composer.ops.attention import flash_attention
from tpu_composer.models import ModelConfig
from tpu_composer.parallel import (
    TrainConfig, abstract_train_state, make_train_step, solve_mesh_axes,
)

out = {}

# Same flock the xdist AOT suites take: concurrent libtpu topology inits
# abort on libtpu's own multi-process lockfile, so every device-less AOT
# user queues on this lock instead of racing.
from tpu_composer.workload.libtpu_serial import libtpu_serialized

t0 = time.time()
with libtpu_serialized():
    dev = topologies.get_topology_desc("v5e:2x2", "tpu").devices[0]
q = jax.ShapeDtypeStruct((2, 2048, 4, 128), jnp.bfloat16,
                         sharding=SingleDeviceSharding(dev))
loss = lambda q, k, v: flash_attention(
    q, k, v, causal=True, interpret=False).astype(jnp.float32).sum()
jax.jit(jax.grad(loss, argnums=(0, 1, 2))).lower(q, q, q).compile()
out["flash_grad_v5e"] = {"ok": True, "seconds": round(time.time() - t0, 2),
                         "shape": "B2 S2048 H4 D128 bf16 causal"}

from tpu_composer.workload.hlo_collectives import summarize_compiled

# Per-axis collective traffic of a compiled step (bytes, op counts): the
# compiled-program evidence behind the multi-chip claims (VERDICT r4 ask
# #4). Compact: per-axis totals + op counts, not the per-instance table.
def _collectives(compiled, axes, mesh):
    s = summarize_compiled(compiled, axes, mesh)
    return {"per_axis_bytes": s["per_axis_bytes"],
            "op_counts": s["op_counts"],
            "total_bytes": s["total_bytes"]}

t0 = time.time()
with libtpu_serialized():
    devs = topologies.get_topology_desc("v5e:2x4", "tpu").devices
axes = solve_mesh_axes(8, sp=2, tp=2)
mesh = Mesh(np.array(devs).reshape([axes[a] for a in axes]), tuple(axes))
tc = TrainConfig(
    model=ModelConfig(vocab_size=512, d_model=128, n_layers=2, n_heads=4,
                      d_ff=256, max_seq=64, dtype=jnp.bfloat16),
    sp_impl="zigzag",
)
state = abstract_train_state(tc, mesh)
step_fn, batch_sharding = make_train_step(tc, mesh)
tokens = jax.ShapeDtypeStruct((2 * axes["dp"], 64), jnp.int32,
                              sharding=batch_sharding)
compiled_8 = step_fn.lower(state, tokens).compile()
out["train_step_v5e_2x4"] = {
    "ok": True, "seconds": round(time.time() - t0, 2),
    "mesh": dict(axes), "sp_impl": "zigzag",
    "collectives": _collectives(compiled_8, axes, mesh),
}

# 16-chip expert-parallel step (v5e 4x4): the ep all-to-all/all-gather
# dispatch traffic per axis, recorded from the compiled program. Guarded:
# a regression here must not discard the 8-chip evidence above.
t0 = time.time()
try:
    from tpu_composer.models import MoEConfig

    with libtpu_serialized():
        devs16 = topologies.get_topology_desc("v5e:4x4", "tpu").devices
    axes16 = solve_mesh_axes(16, ep=2, sp=2, tp=2)
    mesh16 = Mesh(np.array(devs16).reshape([axes16[a] for a in axes16]),
                  tuple(axes16))
    tc16 = TrainConfig(
        model=MoEConfig(vocab_size=512, d_model=128, n_layers=2, n_heads=4,
                        d_ff=256, max_seq=64, dtype=jnp.bfloat16,
                        n_experts=4, top_k=2, capacity_factor=2.0,
                        moe_period=2)
    )
    state16 = abstract_train_state(tc16, mesh16)
    step16, bs16 = make_train_step(tc16, mesh16)
    toks16 = jax.ShapeDtypeStruct(
        (2 * axes16["dp"] * axes16["ep"], 64), jnp.int32, sharding=bs16
    )
    compiled_16 = step16.lower(state16, toks16).compile()
    out["moe_train_step_v5e_4x4"] = {
        "ok": True, "seconds": round(time.time() - t0, 2),
        "mesh": dict(axes16),
        "collectives": _collectives(compiled_16, axes16, mesh16),
    }
except Exception as e:
    out["moe_train_step_v5e_4x4"] = {
        "ok": False, "seconds": round(time.time() - t0, 2),
        "error": f"{type(e).__name__}: {e}",
    }

# HBM-fit check for the bench's MXU-sized qualify config: the compiled
# program's own memory accounting vs a v5e chip's 16 GB, so the bench
# cannot OOM-surprise on the one day the chip is reachable.
t0 = time.time()
os.environ["TPUC_FLASH_INTERPRET"] = "0"
axes1 = solve_mesh_axes(1)
mesh1 = Mesh(np.array(devs[:1]).reshape([axes1[a] for a in axes1]),
             tuple(axes1))
big = ModelConfig(vocab_size=32768, d_model=2048, n_layers=4, n_heads=16,
                  d_ff=8192, max_seq=2048, dtype=jnp.bfloat16,
                  attn_impl="flash")
tc1 = TrainConfig(model=big)
state1 = abstract_train_state(tc1, mesh1)
step1, bs1 = make_train_step(tc1, mesh1)
toks1 = jax.ShapeDtypeStruct((8, 2048), jnp.int32, sharding=bs1)
compiled1 = step1.lower(state1, toks1).compile()
ma = compiled1.memory_analysis()
peak = (ma.argument_size_in_bytes + ma.temp_size_in_bytes
        + ma.generated_code_size_in_bytes
        + ma.output_size_in_bytes - ma.alias_size_in_bytes)
out["qualify_large_hbm"] = {
    "ok": peak < 0.9 * 16 * 1024**3,
    "peak_gib": round(peak / 2**30, 2),
    "hbm_gib": 16,
    "seconds": round(time.time() - t0, 2),
}
# XLA's own flop count for the MFU-stage program: the probe's mfu field
# divides by a HAND-derived 6*N*tokens estimate (acceptance.py); recording
# the compiler's count validates that denominator with compiled-program
# evidence and yields the physics floor on step time at v5e bf16 peak.
try:
    ca = compiled1.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    xla_flops = float(ca.get("flops", 0.0))
    if xla_flops > 0:
        # Raw compiler flops first: they must survive even if the shared
        # peak-TFLOPS lookup below ever fails in the child env.
        out["qualify_large_hbm"]["xla_flops_per_step"] = xla_flops
        from tpu_composer.workload.acceptance import _BF16_PEAK_TFLOPS
        _peak_tflops = dict(_BF16_PEAK_TFLOPS)["TPU v5e"]
        out["qualify_large_hbm"]["min_step_ms_at_v5e_peak"] = round(
            xla_flops / (_peak_tflops * 1e12) * 1e3, 2
        )
except Exception:  # noqa: BLE001 - cost model availability varies by backend
    pass

# Serving path: the decode-stage model's generate() programs (bf16 and the
# fully-quantized int8-weights + int8-KV variant) compile for the v5e
# target — the whole prefill + lax.scan decode loop lowers through
# XLA:TPU, so the serving claims carry compile evidence on relay-dead
# rounds too. Guarded: a regression in this newest target must not
# discard the three compile-evidence targets already in ``out``.
t0 = time.time()
try:
    from tpu_composer.models.decode import generate
    from tpu_composer.models.quant import quantize_decode_params
    from tpu_composer.models.transformer import init_params

    def abs_on_dev(tree):
        return jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                x.shape, x.dtype, sharding=SingleDeviceSharding(devs[0])
            ),
            tree,
        )

    sc = ModelConfig(vocab_size=32768, d_model=1024, n_layers=8, n_heads=16,
                     n_kv_heads=4, d_ff=4096, max_seq=256,
                     dtype=jnp.bfloat16)
    prompt = jax.ShapeDtypeStruct((8, 128), jnp.int32,
                                  sharding=SingleDeviceSharding(devs[0]))
    sp0 = jax.eval_shape(lambda: init_params(sc, jax.random.key(0)))
    jax.jit(
        lambda pp, tk: generate(pp, tk, sc, max_new_tokens=128)
    ).lower(abs_on_dev(sp0), prompt).compile()
    qp = abs_on_dev(jax.eval_shape(quantize_decode_params, sp0))
    jax.jit(
        lambda pp, tk: generate(pp, tk, sc, max_new_tokens=128,
                                kv_quant=True)
    ).lower(qp, prompt).compile()
    out["decode_serving_v5e"] = {
        "ok": True, "seconds": round(time.time() - t0, 2),
        "model": "d1024 L8 H16 kv4 ff4096",
        "variants": ["bf16", "int8_w_int8_kv"],
    }
except Exception as e:
    out["decode_serving_v5e"] = {
        "ok": False, "seconds": round(time.time() - t0, 2),
        "error": f"{type(e).__name__}: {e}",
    }
print("AOT_RESULT " + json.dumps(out), flush=True)
"""


def aot_compile_probe(env: Dict[str, str], timeout_s: float = 420.0) -> Dict[str, Any]:
    """AOT-compile the flash kernels + the 8-chip sharded train step for a
    real v5e topology in a CPU-backend subprocess. Returns per-target
    timings, or {error/stderr_tail} — never raises, bounded by timeout_s.
    Same pipeline as tests/test_flash_aot_tpu.py / test_multichip_aot_tpu.py,
    run at bench time so BENCH artifacts carry compile evidence for rounds
    where the chip itself is unreachable."""
    child_env = dict(env)
    child_env["JAX_PLATFORMS"] = "cpu"
    try:
        proc = subprocess.run(
            [sys.executable, "-u", "-c", _AOT_CHILD],
            capture_output=True, text=True, timeout=timeout_s, env=child_env,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"timeout after {timeout_s}s"}
    for line in proc.stdout.splitlines():
        if line.startswith("AOT_RESULT "):
            return json.loads(line[len("AOT_RESULT "):])
    return {
        "error": f"exit {proc.returncode}",
        "stderr_tail": proc.stderr.strip().splitlines()[-15:],
    }


def _drive_child(
    env: Dict[str, str], timeouts: Dict[str, float], order: List[str]
) -> Tuple[Dict[str, Any], List[str], Optional[str], List[str]]:
    """One subprocess pass over the post-devnodes stages: returns
    (stages, completed, failed_stage, stderr_tail)."""
    stages: Dict[str, Any] = {}
    completed: List[str] = []

    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", _CHILD],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
    )

    stderr_buf: List[str] = []
    # Reader threads named for profiler attribution (caught by tpuc-lint
    # named-threads).
    t_err = threading.Thread(
        target=lambda: stderr_buf.extend(proc.stderr),  # type: ignore[arg-type]
        name="probe-stderr-reader", daemon=True,
    )
    t_err.start()

    lines: "list[str]" = []
    done = threading.Event()

    def reader():
        for line in proc.stdout:  # type: ignore[union-attr]
            lines.append(line)
        done.set()

    t_out = threading.Thread(
        target=reader, name="probe-stdout-reader", daemon=True
    )
    t_out.start()

    failed_stage: Optional[str] = None
    idx = 0
    partials: Dict[str, Any] = {}

    def drain() -> None:
        nonlocal idx
        while idx < len(lines):
            line = lines[idx]
            idx += 1
            if line.startswith("STAGE_RESULT "):
                rec = json.loads(line[len("STAGE_RESULT "):])
                stages[rec.pop("stage")] = rec
            elif line.startswith("STAGE_PARTIAL "):
                # Provisional evidence a stage emits before entering a
                # risky section (e.g. decode's quant numbers before the
                # speculative bench): preserved if the stage later dies in
                # a way no in-child except can catch (watchdog hard-exit,
                # parent kill); superseded by the stage's final record.
                name, _, payload = line[len("STAGE_PARTIAL "):].partition(" ")
                try:
                    partials[name] = json.loads(payload)
                except ValueError:
                    pass

    for stage in order:
        deadline = time.monotonic() + timeouts[stage]
        while time.monotonic() < deadline:
            drain()
            if stage in stages or done.is_set():
                break
            time.sleep(0.2)
        # The reader may have appended final lines between the last drain and
        # observing done — drain once more before declaring a stage failed.
        drain()
        if stage in stages:
            completed.append(stage)
        else:
            failed_stage = stage
            proc.kill()
            break

    if failed_stage is None:
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        if proc.returncode not in (0, None) and order[-1] not in stages:
            failed_stage = next(s for s in order if s not in stages)

    # Fold in partials for stages that never produced a final record —
    # marked so consumers know the stage died after these numbers.
    for name, rec in partials.items():
        if name not in stages:
            stages[name] = {**rec, "partial": True}

    t_err.join(timeout=5)
    # 40 lines of tail: enough to keep a full faulthandler thread dump (the
    # whole point of the in-child watchdog) plus the verbose PJRT/libtpu
    # breadcrumbs; r02's 6-line tail held one warning and nothing else.
    tail = "".join(stderr_buf).strip().splitlines()[-40:]
    return stages, completed, failed_stage, tail
