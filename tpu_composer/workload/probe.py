"""Staged accelerator probe — produce numbers *or* a named-stage diagnosis.

Round 1's bench ran the whole slice qualification in one subprocess under one
420 s timeout and returned nothing when the device tunnel hung — so the bench
carried zero accelerator evidence (VERDICT.md "What's weak" #1). This module
splits the probe into ordered stages, each reported the moment it completes:

  devnodes      device-node / env / lockfile enumeration (pure os, in-process)
  backend_init  ``jax.devices()`` — PJRT plugin + tunnel handshake
  matmul        one tiny jitted bf16 matmul (compiler + executor round trip)
  flash_attn    Pallas flash fwd+bwd vs the XLA reference (numerics on-chip)
  qualify       full ``qualify_slice`` (allreduce busbw + train-step TFLOPS)

Stages after ``devnodes`` run in ONE subprocess that prints a
``STAGE_RESULT <json>`` line per completed stage; the parent tails the pipe
with a per-stage deadline. A hang therefore costs only the hanging stage's
timeout and still yields every earlier stage's numbers plus the name of the
stage that died and the subprocess's stderr tail.

Reference analog: the reference's only device health probe is `nvidia-smi`
answering over pod-exec (/root/reference/internal/utils/gpus.go:207-239);
it has no staged diagnosis at all — a hang there surfaces as a generic
reconcile timeout.
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional

# Each stage gets its own deadline, measured from the previous stage's
# completion. backend_init dominates: a cold PJRT tunnel handshake plus the
# first compile is the documented slow path.
STAGE_TIMEOUTS_S: Dict[str, float] = {
    "backend_init": 240.0,
    "matmul": 120.0,
    "flash_attn": 240.0,
    "qualify": 300.0,
}

_CHILD = r"""
import json, os, time

def emit(stage, t0, **kv):
    kv["stage"] = stage
    kv["seconds"] = round(time.time() - t0, 2)
    print("STAGE_RESULT " + json.dumps(kv), flush=True)

t0 = time.time()
import jax
# The image's sitecustomize registers the accelerator platform at interpreter
# start and the env var alone is read too late to override it — honor an
# explicit JAX_PLATFORMS through the live config (same dance as
# tests/conftest.py), so CPU smoke runs of this probe exercise every stage.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
devs = jax.devices()
try:
    version = jax.extend.backend.get_backend().platform_version
except Exception:
    version = "unknown"
emit("backend_init", t0, backend=jax.default_backend(),
     n_devices=len(devs), device_kind=devs[0].device_kind,
     platform_version=version)

t0 = time.time()
import jax.numpy as jnp
x = jnp.ones((512, 512), jnp.bfloat16)
y = jax.jit(lambda a: a @ a)(x)
y.block_until_ready()
emit("matmul", t0, ok=True, result_dtype=str(y.dtype))

t0 = time.time()
try:
    from tpu_composer.workload.probe import flash_attention_on_chip
    emit("flash_attn", t0, **flash_attention_on_chip())
except Exception as e:  # noqa: BLE001 - diagnosis, not control flow
    emit("flash_attn", t0, error=f"{type(e).__name__}: {e}")

t0 = time.time()
from tpu_composer.workload.acceptance import qualify_slice
results = qualify_slice(batch=4, seq=512, allreduce_mb=16.0, steps=5)
results["backend"] = jax.default_backend()
emit("qualify", t0, **results)
"""


def probe_devnodes() -> Dict[str, Any]:
    """Stage a: what does the host itself say about accelerators?

    Pure filesystem/env enumeration — cannot hang, runs in-process. Mirrors
    what `native/tpunode.cc` scans, plus the libtpu/PJRT environment that
    decides which backend ``jax.devices()`` will try to bring up.
    """
    out: Dict[str, Any] = {
        "accel_nodes": sorted(glob.glob("/dev/accel*")),
        "vfio_nodes": sorted(glob.glob("/dev/vfio/*")),
        "libtpu_lockfile": os.path.exists("/tmp/libtpu_lockfile"),
        "env": {
            k: v
            for k, v in os.environ.items()
            if k.startswith(("JAX_", "TPU_", "XLA_", "PJRT_", "LIBTPU"))
            or "AXON" in k
        },
    }
    try:
        import importlib.util

        out["libtpu_installed"] = importlib.util.find_spec("libtpu") is not None
    except Exception:
        out["libtpu_installed"] = False
    return out


def flash_attention_on_chip(
    batch: int = 2, heads: int = 4, seq: int = 1024, head_dim: int = 128
) -> Dict[str, Any]:
    """Validate the Pallas flash kernels on the live backend (VERDICT #4).

    Runs fwd+bwd through both the flash path and the XLA einsum reference,
    asserts numerics, and times both at the given seq. Only meaningful on a
    TPU backend (Mosaic lowering); on CPU it reports the backend and skips.
    """
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return {"skipped": f"backend is {jax.default_backend()}, not tpu"}

    from tpu_composer.ops.attention import flash_attention, mha_reference

    key = jax.random.key(0)
    kq, kk, kv = jax.random.split(key, 3)
    shape = (batch, heads, seq, head_dim)
    q = jax.random.normal(kq, shape, jnp.bfloat16)
    k = jax.random.normal(kk, shape, jnp.bfloat16)
    v = jax.random.normal(kv, shape, jnp.bfloat16)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, causal=True).astype(jnp.float32).sum()

    def loss_ref(q, k, v):
        return mha_reference(q, k, v, causal=True).astype(jnp.float32).sum()

    f_fwd = jax.jit(lambda *a: flash_attention(*a, causal=True))
    r_fwd = jax.jit(lambda *a: mha_reference(*a, causal=True))
    f_grad = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))
    r_grad = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))

    of = f_fwd(q, k, v).block_until_ready()
    orf = r_fwd(q, k, v).block_until_ready()
    fwd_err = float(
        jnp.max(jnp.abs(of.astype(jnp.float32) - orf.astype(jnp.float32)))
    )
    gf = jax.block_until_ready(f_grad(q, k, v))
    gr = jax.block_until_ready(r_grad(q, k, v))
    bwd_err = max(
        float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))
        for a, b in zip(gf, gr)
    )

    def bench(fn, *args, iters=20):
        fn(*args)  # warm
        jax.block_until_ready(fn(*args))
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        jax.block_until_ready(out)
        return (time.perf_counter() - t0) / iters * 1e3

    flash_ms = bench(f_fwd, q, k, v)
    ref_ms = bench(r_fwd, q, k, v)
    flash_bwd_ms = bench(f_grad, q, k, v)
    ref_bwd_ms = bench(r_grad, q, k, v)

    # bf16 tolerance: sums over seq-length dot products accumulate ~1e-2.
    ok = fwd_err < 0.1 and bwd_err < 0.5
    return {
        "numerics_ok": ok,
        "fwd_max_err": round(fwd_err, 5),
        "bwd_max_err": round(bwd_err, 5),
        "seq": seq,
        "flash_fwd_ms": round(flash_ms, 3),
        "ref_fwd_ms": round(ref_ms, 3),
        "flash_bwd_ms": round(flash_bwd_ms, 3),
        "ref_bwd_ms": round(ref_bwd_ms, 3),
        "fwd_speedup": round(ref_ms / flash_ms, 2),
        "bwd_speedup": round(ref_bwd_ms / flash_bwd_ms, 2),
    }


def staged_accelerator_probe(
    repo_root: Optional[str] = None,
    timeouts: Optional[Dict[str, float]] = None,
) -> Dict[str, Any]:
    """Run all stages; return {stages: {...}, completed: [...], failed_stage,
    diagnosis}. Never raises, never hangs past the per-stage deadlines."""
    timeouts = {**STAGE_TIMEOUTS_S, **(timeouts or {})}
    stages: Dict[str, Any] = {"devnodes": probe_devnodes()}
    completed: List[str] = ["devnodes"]
    order = ["backend_init", "matmul", "flash_attn", "qualify"]

    env = dict(os.environ)
    root = repo_root or os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")

    proc = subprocess.Popen(
        [sys.executable, "-u", "-c", _CHILD],
        stdout=subprocess.PIPE,
        stderr=subprocess.PIPE,
        text=True,
        env=env,
    )

    stderr_buf: List[str] = []
    t_err = threading.Thread(
        target=lambda: stderr_buf.extend(proc.stderr), daemon=True  # type: ignore[arg-type]
    )
    t_err.start()

    lines: "list[str]" = []
    done = threading.Event()

    def reader():
        for line in proc.stdout:  # type: ignore[union-attr]
            lines.append(line)
        done.set()

    t_out = threading.Thread(target=reader, daemon=True)
    t_out.start()

    failed_stage: Optional[str] = None
    idx = 0

    def drain() -> None:
        nonlocal idx
        while idx < len(lines):
            line = lines[idx]
            idx += 1
            if line.startswith("STAGE_RESULT "):
                rec = json.loads(line[len("STAGE_RESULT "):])
                stages[rec.pop("stage")] = rec

    for stage in order:
        deadline = time.monotonic() + timeouts[stage]
        while time.monotonic() < deadline:
            drain()
            if stage in stages or done.is_set():
                break
            time.sleep(0.2)
        # The reader may have appended final lines between the last drain and
        # observing done — drain once more before declaring a stage failed.
        drain()
        if stage in stages:
            completed.append(stage)
        else:
            failed_stage = stage
            proc.kill()
            break

    if failed_stage is None:
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
        if proc.returncode not in (0, None) and order[-1] not in stages:
            failed_stage = next(s for s in order if s not in stages)

    t_err.join(timeout=5)
    result: Dict[str, Any] = {"stages": stages, "completed": completed}
    if failed_stage:
        result["failed_stage"] = failed_stage
        tail = "".join(stderr_buf).strip().splitlines()[-6:]
        result["diagnosis"] = {
            "timeout_s": timeouts.get(failed_stage),
            "stderr_tail": tail,
            "libtpu_lockfile": os.path.exists("/tmp/libtpu_lockfile"),
            "accel_nodes_present": bool(stages["devnodes"]["accel_nodes"]),
        }
    return result
