"""Relay watcher — capture on-chip evidence the moment the TPU tunnel answers.

The axon tunnel relay behind ``PALLAS_AXON_POOL_IPS`` dies for whole rounds
(r03/r04: every end-of-round bench found it down, so zero live-TPU numbers
landed despite the full probe harness being ready). The failure mode is
timing: the relay's uptime windows never coincided with a bench run. This
watcher removes the coincidence requirement — started at round begin, it
polls the relay endpoints with pure bounded sockets every ``poll_s`` and, on
the first poll that finds an endpoint accepting TCP, fires the full staged
probe (``workload/probe.py``) and archives the result to
``bench_artifacts/last_tpu_probe.json``, which ``bench.py`` attaches to the
round artifact whenever the end-of-round probe itself cannot reach the chip.

Every poll is appended to ``bench_artifacts/relay_watch.jsonl`` — if the
relay never answers, that attempt log is the round's evidence that the
outage, not the harness, withheld the numbers.

Reference analog: none — the reference has no hardware-evidence capture at
all (SURVEY.md §6: it publishes no benchmark numbers). This subsystem exists
because our bar does.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, Optional

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
ARTIFACT_DIR = os.path.join(REPO_ROOT, "bench_artifacts")
ARCHIVE_PATH = os.path.join(ARTIFACT_DIR, "last_tpu_probe.json")
LOG_PATH = os.path.join(ARTIFACT_DIR, "relay_watch.jsonl")
PID_PATH = os.path.join(ARTIFACT_DIR, "relay_watch.pid")


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def archive_tpu_probe(result: Dict[str, Any], note: str,
                      path: str = ARCHIVE_PATH) -> None:
    """Write a staged-probe result as the canonical on-TPU archive record.

    Shared by bench.py (end-of-round live capture) and this watcher
    (mid-round opportunistic capture) so both produce the same shape the
    bench attaches on relay-dead rounds.

    Quality-guarded: a PARTIAL capture (relay flapped mid-probe) never
    replaces an archived FULL capture — the best hardware evidence of the
    round must survive later, worse attempts by either caller."""
    if not probe_is_full_tpu_capture(result):
        try:
            with open(path) as f:
                existing = json.load(f)
        except (OSError, ValueError):
            existing = None
        if existing is not None and probe_is_full_tpu_capture(existing):
            return
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(
            {
                "captured_at": _now(),
                "note": note,
                "stages": result.get("stages", {}),
                "completed": result.get("completed", []),
                "failed_stage": result.get("failed_stage"),
            },
            f, indent=1,
        )
    os.replace(tmp, path)


def probe_is_full_tpu_capture(result: Dict[str, Any]) -> bool:
    """True when the probe ran on backend=tpu and every evidence stage the
    VERDICT asks for landed: the flash sweep with long-seq headline fields,
    qualify_large, and the decode bench."""
    stages = result.get("stages", {})
    if stages.get("backend_init", {}).get("backend") != "tpu":
        return False
    completed = set(result.get("completed", []))
    if not {"flash_attn", "qualify", "qualify_large", "decode"} <= completed:
        return False
    return "fwd_speedup_long" in stages.get("flash_attn", {})


def _log(rec: Dict[str, Any], log_path: str) -> None:
    rec = {"t": _now(), **rec}
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    with open(log_path, "a") as f:
        f.write(json.dumps(rec) + "\n")


def _proc_start_time(pid: int) -> Optional[str]:
    """Kernel start-time of a pid (field 22 of /proc/<pid>/stat) — the
    exact pid-reuse discriminator: a recycled pid has a different start
    time. None when unreadable (no /proc, or the process is gone)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            stat = f.read()
    except OSError:
        return None
    # comm (field 2) may contain spaces/parens: split after the LAST ')'.
    fields = stat.rsplit(")", 1)[-1].split()
    return fields[19] if len(fields) > 19 else None


def _write_pidfile(pid_path: str) -> None:
    with open(pid_path, "w") as f:
        start = _proc_start_time(os.getpid()) or ""
        f.write(f"{os.getpid()} {start}")


def _another_watcher_alive(pid_path: str) -> Optional[int]:
    try:
        with open(pid_path) as f:
            parts = f.read().split()
        pid = int(parts[0])
        recorded_start = parts[1] if len(parts) > 1 else None
    except (OSError, ValueError, IndexError):
        return None
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return None  # stale pidfile, process gone
    except PermissionError:
        pass  # alive, owned by another user — still a live watcher
    except OSError:
        return None
    # A SIGKILL'd watcher leaves its pidfile behind; if the pid has since
    # been recycled by an unrelated process, its kernel start time cannot
    # match the one recorded at pidfile-write. No cmdline heuristics in
    # this path — an embedded watcher (tests, another operator process)
    # is a watcher too.
    if recorded_start:
        current = _proc_start_time(pid)
        if current is not None and current != recorded_start:
            return None
        return pid
    # Legacy pid-only pidfile (or a platform without /proc at write time):
    # no start time to compare, so a recycled pid would block every future
    # watcher forever. Fall back to a cmdline check for the watcher's
    # module path (how `make watch-relay` runs it) — "relay_watch" alone
    # would also match e.g. a pytest invocation naming the TEST file.
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            if b"tpu_composer.workload.relay_watch" not in f.read():
                return None
    except OSError:
        pass  # no /proc: err on the safe side, treat as alive
    return pid


def watch_relay(
    poll_s: float = 60.0,
    max_hours: float = 11.5,
    min_capture_gap_s: float = 600.0,
    log_path: str = LOG_PATH,
    archive_path: str = ARCHIVE_PATH,
    pid_path: str = PID_PATH,
    once: bool = False,
) -> int:
    """Poll until the relay answers, then capture; exit 0 after a full
    capture (all evidence stages on backend=tpu), 1 on deadline with no
    relay, 2 if another watcher already runs.

    A partial capture (relay flapped mid-probe) is still archived — it
    supersedes nothing-at-all — and the watcher keeps polling, retrying a
    capture no more often than ``min_capture_gap_s``."""
    from tpu_composer.workload.probe import (
        probe_pool_endpoints,
        staged_accelerator_probe,
    )

    other = _another_watcher_alive(pid_path)
    if other is not None:
        print(f"relay_watch: already running as pid {other}", file=sys.stderr)
        return 2
    os.makedirs(os.path.dirname(pid_path), exist_ok=True)
    _write_pidfile(pid_path)

    deadline = time.monotonic() + max_hours * 3600.0
    last_capture_at = -float("inf")
    polls = 0
    _log({"event": "start", "pid": os.getpid(), "poll_s": poll_s,
          "max_hours": max_hours}, log_path)
    try:
        while time.monotonic() < deadline:
            eps = probe_pool_endpoints()
            up = [e["endpoint"] for e in eps if e.get("reachable")]
            polls += 1
            _log({"up": bool(up), "reachable": up, "poll": polls}, log_path)
            if up and time.monotonic() - last_capture_at >= min_capture_gap_s:
                last_capture_at = time.monotonic()
                _log({"event": "capture_start", "reachable": up}, log_path)
                result = staged_accelerator_probe(repo_root=REPO_ROOT)
                backend = (
                    result.get("stages", {})
                    .get("backend_init", {})
                    .get("backend")
                )
                full = probe_is_full_tpu_capture(result)
                _log(
                    {
                        "event": "capture_done",
                        "backend": backend,
                        "completed": result.get("completed", []),
                        "failed_stage": result.get("failed_stage"),
                        "full": full,
                    },
                    log_path,
                )
                if backend == "tpu":
                    archive_tpu_probe(
                        result,
                        note=(
                            "Live on-TPU staged probe captured mid-round by "
                            "the relay watcher (workload/relay_watch.py) the "
                            "moment the axon tunnel relay answered. All "
                            "numbers ran on backend=tpu."
                        ),
                        path=archive_path,
                    )
                    if full or once:
                        _log({"event": "exit", "reason": "capture_complete"},
                             log_path)
                        return 0
            time.sleep(poll_s)
        _log({"event": "exit", "reason": "deadline", "polls": polls}, log_path)
        return 1
    finally:
        try:
            os.unlink(pid_path)
        except OSError:
            pass


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--poll-s", type=float, default=60.0)
    p.add_argument("--max-hours", type=float, default=11.5)
    p.add_argument("--min-capture-gap-s", type=float, default=600.0)
    p.add_argument("--once", action="store_true",
                   help="exit after the first backend=tpu capture, full or not")
    a = p.parse_args(argv)
    return watch_relay(poll_s=a.poll_s, max_hours=a.max_hours,
                       min_capture_gap_s=a.min_capture_gap_s, once=a.once)


if __name__ == "__main__":
    sys.exit(main())
