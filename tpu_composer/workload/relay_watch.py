"""Relay watcher — capture on-chip evidence the moment the TPU tunnel answers.

The axon tunnel relay behind ``PALLAS_AXON_POOL_IPS`` dies for whole rounds
(r03/r04: every end-of-round bench found it down, so zero live-TPU numbers
landed despite the full probe harness being ready). The failure mode is
timing: the relay's uptime windows never coincided with a bench run. This
watcher removes the coincidence requirement — started at round begin, it
polls the relay endpoints with pure bounded sockets every ``poll_s`` and, on
the first poll that finds an endpoint accepting TCP, fires the full staged
probe (``workload/probe.py``) and archives the result to
``bench_artifacts/last_tpu_probe.json``, which ``bench.py`` attaches to the
round artifact whenever the end-of-round probe itself cannot reach the chip.

Every poll is appended to ``bench_artifacts/relay_watch.jsonl`` — if the
relay never answers, that attempt log is the round's evidence that the
outage, not the harness, withheld the numbers.

Reference analog: none — the reference has no hardware-evidence capture at
all (SURVEY.md §6: it publishes no benchmark numbers). This subsystem exists
because our bar does.
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys
import time
from typing import Any, Dict, Optional

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
ARTIFACT_DIR = os.path.join(REPO_ROOT, "bench_artifacts")
ARCHIVE_PATH = os.path.join(ARTIFACT_DIR, "last_tpu_probe.json")
LOG_PATH = os.path.join(ARTIFACT_DIR, "relay_watch.jsonl")
PID_PATH = os.path.join(ARTIFACT_DIR, "relay_watch.pid")


def _now() -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())


def archive_tpu_probe(result: Dict[str, Any], note: str,
                      path: str = ARCHIVE_PATH) -> None:
    """Write a staged-probe result as the canonical on-TPU archive record.

    Shared by bench.py (end-of-round live capture) and this watcher
    (mid-round opportunistic capture) so both produce the same shape the
    bench attaches on relay-dead rounds.

    Quality-guarded: a PARTIAL capture (relay flapped mid-probe) never
    replaces an archived FULL capture — the best hardware evidence of the
    round must survive later, worse attempts by either caller."""
    if not probe_is_full_tpu_capture(result):
        try:
            with open(path) as f:
                existing = json.load(f)
        except (OSError, ValueError):
            existing = None
        if existing is not None and probe_is_full_tpu_capture(existing):
            return
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(
            {
                "captured_at": _now(),
                "note": note,
                "stages": result.get("stages", {}),
                "completed": result.get("completed", []),
                "failed_stage": result.get("failed_stage"),
            },
            f, indent=1,
        )
    os.replace(tmp, path)


def probe_is_full_tpu_capture(result: Dict[str, Any]) -> bool:
    """True when the probe ran on backend=tpu and every evidence stage the
    VERDICT asks for landed: the flash sweep with long-seq headline fields,
    qualify_large, and the decode bench."""
    stages = result.get("stages", {})
    if stages.get("backend_init", {}).get("backend") != "tpu":
        return False
    completed = set(result.get("completed", []))
    if not {"flash_attn", "qualify", "qualify_large", "decode"} <= completed:
        return False
    return "fwd_speedup_long" in stages.get("flash_attn", {})


def _log(rec: Dict[str, Any], log_path: str) -> None:
    rec = {"t": _now(), **rec}
    os.makedirs(os.path.dirname(log_path), exist_ok=True)
    with open(log_path, "a") as f:
        f.write(json.dumps(rec) + "\n")


def _proc_start_time(pid: int) -> Optional[str]:
    """Kernel start-time of a pid (field 22 of /proc/<pid>/stat) — the
    exact pid-reuse discriminator: a recycled pid has a different start
    time. None when unreadable (no /proc, or the process is gone)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            stat = f.read()
    except OSError:
        return None
    # comm (field 2) may contain spaces/parens: split after the LAST ')'.
    fields = stat.rsplit(")", 1)[-1].split()
    return fields[19] if len(fields) > 19 else None


def _write_pidfile(pid_path: str) -> None:
    with open(pid_path, "w") as f:
        start = _proc_start_time(os.getpid()) or ""
        f.write(f"{os.getpid()} {start}")


def _pid_alive_with_start(pid: int, recorded_start: Optional[str]) -> bool:
    """Is ``pid`` alive AND (when a start time was recorded) still the same
    process — i.e. its /proc start time matches? The start-time comparison
    is the pid-reuse discriminator: a SIGKILL'd process leaves its pid/
    marker file behind, and a recycled pid must not read as alive.
    ``recorded_start`` falsy skips the reuse check (caller decides how to
    handle legacy records)."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False  # stale record, process gone
    except PermissionError:
        pass  # alive, owned by another user
    except OSError:
        return False
    if recorded_start:
        current = _proc_start_time(pid)
        if current is not None and current != recorded_start:
            return False
    return True


def _another_watcher_alive(pid_path: str) -> Optional[int]:
    try:
        with open(pid_path) as f:
            parts = f.read().split()
        pid = int(parts[0])
        recorded_start = parts[1] if len(parts) > 1 else None
    except (OSError, ValueError, IndexError):
        return None
    # No cmdline heuristics on the start-time path — an embedded watcher
    # (tests, another operator process) is a watcher too.
    if not _pid_alive_with_start(pid, recorded_start):
        return None
    if recorded_start:
        return pid
    # Legacy pid-only pidfile (or a platform without /proc at write time):
    # no start time to compare, so a recycled pid would block every future
    # watcher forever. Fall back to a cmdline check for the watcher's
    # module path (how `make watch-relay` runs it) — "relay_watch" alone
    # would also match e.g. a pytest invocation naming the TEST file.
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            if b"tpu_composer.workload.relay_watch" not in f.read():
                return None
    except OSError:
        pass  # no /proc: err on the safe side, treat as alive
    return pid


CAPTURE_MARKER_PATH = os.path.join(ARTIFACT_DIR, "capture_in_progress.json")

#: Three-state result of a marker claim. The distinction between ACQUIRED
#: and UNGUARDED matters on the release path: only a marker THIS process
#: created may be unlinked on exit — a transient OSError used to collapse
#: into the same True as a real claim, and the exit path would then delete
#: a live peer's marker, un-serializing the very handshakes the marker
#: exists to serialize.
MARKER_ACQUIRED = "acquired"
MARKER_HELD = "held-by-other"
MARKER_UNGUARDED = "unguarded"


def _clear_capture(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


def _try_acquire_marker(path: str) -> str:
    """Atomically create the capture marker (O_CREAT|O_EXCL — the check and
    the claim are one syscall, so two clients cannot both win the race a
    plain check-then-write leaves open). A marker that already exists but
    is stale (dead/recycled pid, or this pid's own crash leftover) is
    reaped and the claim retried once.

    Returns one of three states: MARKER_ACQUIRED (this process owns the
    marker and must unlink it when done), MARKER_HELD (another live client
    owns it — do not dial), MARKER_UNGUARDED (the filesystem refused the
    marker entirely; proceed without serialization — a broken marker dir
    must not cost a round's only capture window — but NEVER unlink, since
    any marker on disk belongs to someone else)."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    for _ in range(2):
        try:
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            if capture_in_progress(path):
                return MARKER_HELD
            _clear_capture(path)  # stale: reap, then retry the claim
            continue
        except OSError:
            return MARKER_UNGUARDED
        with os.fdopen(fd, "w") as f:
            json.dump({"pid": os.getpid(),
                       "start": _proc_start_time(os.getpid()),
                       "t": _now()}, f)
        return MARKER_ACQUIRED
    return MARKER_HELD


@contextlib.contextmanager
def hold_capture_marker(path: str = CAPTURE_MARKER_PATH):
    """Serialize PJRT clients: yields True while this process may dial the
    relay (marker acquired, or the filesystem cannot host a marker at
    all), False when another live client holds it — the caller must then
    NOT dial (overlapping handshakes have wedged the relay, r05). The one
    shared acquisition protocol for the watcher and bench.py. On exit the
    marker is unlinked ONLY when this process actually created it."""
    state = _try_acquire_marker(path)
    try:
        yield state != MARKER_HELD
    finally:
        if state == MARKER_ACQUIRED:
            _clear_capture(path)


def capture_in_progress(path: str = CAPTURE_MARKER_PATH) -> bool:
    """True while ANOTHER process's staged probe owns the relay. The axon
    relay has wedged on concurrent PJRT handshakes (r05), so any would-be
    client — watcher or bench — must wait this marker out rather than dial
    in parallel. Stale markers (crashed writer, recycled pid) and the
    caller's own marker (a crash-leftover from this very pid cannot be a
    concurrent client) read as False."""
    try:
        with open(path) as f:
            rec = json.load(f)
        pid = int(rec["pid"])
    except (OSError, ValueError, KeyError, TypeError):
        return False
    if pid == os.getpid():
        return False
    return _pid_alive_with_start(pid, rec.get("start"))


def wait_for_capture_idle(timeout_s: float = 1800.0,
                          path: str = CAPTURE_MARKER_PATH,
                          poll_s: float = 10.0) -> bool:
    """Block until no watcher capture is in flight (True) or timeout_s
    elapses (False). bench.py calls this before its own staged probe so an
    end-of-round bench never handshakes concurrently with a mid-round
    watcher capture — the overlap has wedged the relay for both."""
    deadline = time.monotonic() + timeout_s
    while capture_in_progress(path):
        if time.monotonic() >= deadline:
            return False
        time.sleep(poll_s)
    return True


def watch_relay(
    poll_s: float = 60.0,
    max_hours: float = 11.5,
    min_capture_gap_s: float = 600.0,
    log_path: str = LOG_PATH,
    archive_path: str = ARCHIVE_PATH,
    pid_path: str = PID_PATH,
    marker_path: str = CAPTURE_MARKER_PATH,
    once: bool = False,
) -> int:
    """Poll until the relay answers, then capture; exit 0 after a full
    capture (all evidence stages on backend=tpu), 1 on deadline with no
    relay, 2 if another watcher already runs.

    A partial capture (relay flapped mid-probe) is still archived — it
    supersedes nothing-at-all — and the watcher keeps polling, retrying a
    capture no more often than ``min_capture_gap_s``."""
    from tpu_composer.workload.probe import (
        loopback_relay_mode,
        probe_pool_endpoints,
        staged_accelerator_probe,
    )

    other = _another_watcher_alive(pid_path)
    if other is not None:
        print(f"relay_watch: already running as pid {other}", file=sys.stderr)
        return 2
    os.makedirs(os.path.dirname(pid_path), exist_ok=True)
    _write_pidfile(pid_path)

    deadline = time.monotonic() + max_hours * 3600.0
    last_capture_at = -float("inf")
    last_negative_fallback_at = -float("inf")
    # A failed loopback attempt costs a real (bounded) PJRT handshake, so
    # in the chip-down state — the state the watcher exists to wait out —
    # attempts run on a cooldown (the capture gap only prices attempts
    # that actually reached the tpu backend). 180 s + the ≤90 s attempt
    # itself ≈ one dial every ~4.5 min: tight enough to catch an uptime
    # window the size of r05's observed ~6 min one, bounded enough not to
    # hammer a wedged relay with kill-mid-handshake churn.
    negative_fallback_cooldown_s = 180.0
    # Mutual exclusion is keyed on marker_path — the module-level
    # CAPTURE_MARKER_PATH by default, NOT a path derived from
    # archive_path: a watcher pointed at a non-default archive must still
    # exclude a concurrently-running bench probe, which always serializes
    # on the canonical marker.
    polls = 0
    _log({"event": "start", "pid": os.getpid(), "poll_s": poll_s,
          "max_hours": max_hours}, log_path)
    try:
        while time.monotonic() < deadline:
            capture_possible = (
                time.monotonic() - last_capture_at >= min_capture_gap_s
            )
            eps = probe_pool_endpoints()
            up = [e["endpoint"] for e in eps if e.get("reachable")]
            # Loopback relay: in-process with the PJRT plugin, no TCP
            # listener — an all-refused preflight is structurally
            # meaningless (r05: every port refused while the chip
            # answered). The only honest signal is a real PJRT handshake,
            # and a successful handshake is already half a capture — so in
            # loopback mode the watcher attempts the staged probe DIRECTLY
            # (backend_init doubles as the reachability test) instead of
            # spending a separate detection subprocess. One handshake per
            # attempt also matters because the relay has wedged on
            # concurrent/killed-mid-handshake clients (r05: two overlapping
            # inits wedged a relay that had answered seconds earlier).
            cooled = (
                time.monotonic() - last_negative_fallback_at
                >= negative_fallback_cooldown_s
            )
            loopback_attempt = (
                capture_possible and cooled and not up
                and loopback_relay_mode()
            )
            polls += 1
            rec: Dict[str, Any] = {"up": bool(up), "reachable": up,
                                   "poll": polls}
            if loopback_attempt:
                rec["loopback_attempt"] = True
            _log(rec, log_path)
            if (up or loopback_attempt) and capture_possible and cooled:
                with hold_capture_marker(marker_path) as held:
                    if not held:
                        # Another client (an end-of-round bench probe)
                        # already holds the relay; dialing now would be the
                        # documented overlapping-handshake wedge. Its
                        # capture refreshes the same archive — defer,
                        # don't duplicate.
                        _log({"event": "capture_deferred",
                              "reason": "another client holds the relay"},
                             log_path)
                        time.sleep(poll_s)
                        continue
                    prev_capture_at = last_capture_at
                    last_capture_at = time.monotonic()
                    _log({"event": "capture_start",
                          "reachable": up or ["loopback-relay"]}, log_path)
                    kwargs: Dict[str, Any] = {}
                    if loopback_attempt:
                        # Bound the handshake and skip the cpu-fallback/AOT
                        # stages: a dead loopback relay must cost ~a minute
                        # per attempt, not the full probe budget plus
                        # fallback compiles, every capture gap for 11.5 h.
                        # (90 s is ~9× a healthy in-process handshake.)
                        kwargs = dict(timeouts={"backend_init": 90.0},
                                      retries=0, fallbacks=False)
                    result = staged_accelerator_probe(
                        repo_root=REPO_ROOT, **kwargs
                    )
                backend = (
                    result.get("stages", {})
                    .get("backend_init", {})
                    .get("backend")
                )
                full = probe_is_full_tpu_capture(result)
                _log(
                    {
                        "event": "capture_done",
                        "backend": backend,
                        "completed": result.get("completed", []),
                        "failed_stage": result.get("failed_stage"),
                        "full": full,
                    },
                    log_path,
                )
                if backend == "tpu":
                    archive_tpu_probe(
                        result,
                        note=(
                            "Live on-TPU staged probe captured mid-round by "
                            "the relay watcher (workload/relay_watch.py) the "
                            "moment the axon tunnel relay answered. All "
                            "numbers ran on backend=tpu."
                        ),
                        path=archive_path,
                    )
                    if full or once:
                        _log({"event": "exit", "reason": "capture_complete"},
                             log_path)
                        return 0
                else:
                    # A failed handshake — loopback dial or a TCP-path
                    # attempt whose relay died between preflight and
                    # handshake — is a DOWN-relay datum, not a capture: it
                    # pays only the (shorter) cooldown, never the capture
                    # gap. The one observed relay-uptime window (r05) was
                    # ~6 min; a gap-priced failure just before a window
                    # opened would sleep straight through it.
                    last_capture_at = prev_capture_at
                    last_negative_fallback_at = time.monotonic()
            time.sleep(poll_s)
        _log({"event": "exit", "reason": "deadline", "polls": polls}, log_path)
        return 1
    finally:
        try:
            os.unlink(pid_path)
        except OSError:
            pass


def main(argv: Optional[list] = None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--poll-s", type=float, default=60.0)
    p.add_argument("--max-hours", type=float, default=11.5)
    p.add_argument("--min-capture-gap-s", type=float, default=600.0)
    p.add_argument("--once", action="store_true",
                   help="exit after the first backend=tpu capture, full or not")
    a = p.parse_args(argv)
    return watch_relay(poll_s=a.poll_s, max_hours=a.max_hours,
                       min_capture_gap_s=a.min_capture_gap_s, once=a.once)


if __name__ == "__main__":
    sys.exit(main())
