"""Training loop — loader + sharded train step + checkpoint/resume, tied
into one resumable `fit` call.

The user-facing top of the workload layer: everything below it already
exists as composable pieces (data/pipeline.py feeds, parallel/train.py
steps, parallel/checkpoint.py persists); this loop owns the glue rules a
correct resumable run needs:

- **One source of truth for progress**: the checkpointed step. On resume,
  the loader is fast-forwarded to exactly that step (the data stream is a
  pure function of the step — data/pipeline.py), so the restored run
  consumes the same batches the uninterrupted run would have. Losses are
  bit-comparable across a kill/restart (test-pinned).
- **Async-friendly cadence**: metrics are pulled to host only every
  ``log_every`` steps and checkpoints written every ``checkpoint_every``;
  between those, steps stay fully async on device (JAX dispatch pipelining
  — a per-step float(loss) would serialize every step on the tunnel).

The reference's analog is CRDs-as-checkpoint for the control plane
(SURVEY.md §5); the workload side has no analog there — first-class here.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
from jax.sharding import Mesh

from tpu_composer.data.pipeline import PackedLMDataset, ShardedLoader
from tpu_composer.parallel import checkpoint as ckpt
from tpu_composer.parallel.train import (
    TrainConfig,
    make_train_state,
    make_train_step,
)

log = logging.getLogger("tpu_composer.trainer")


@dataclass
class FitResult:
    state: Dict[str, Any]
    step: int
    history: List[Dict[str, float]] = field(default_factory=list)
    resumed_from: Optional[int] = None


def fit(
    tc: TrainConfig,
    mesh: Mesh,
    dataset: PackedLMDataset,
    total_steps: int,
    global_batch: int,
    checkpoint_dir: Optional[str] = None,
    checkpoint_every: int = 0,
    log_every: int = 10,
    seed: int = 0,
) -> FitResult:
    """Train for ``total_steps`` optimizer steps, resuming from the newest
    complete checkpoint under ``checkpoint_dir`` when one exists.

    Returns the final state, the step reached, and the logged metric
    history (step, loss, grad_norm, steps_per_s at each log point).
    """
    if checkpoint_every and not checkpoint_dir:
        raise ValueError("checkpoint_every needs checkpoint_dir")
    step_fn, batch_sharding = make_train_step(tc, mesh)
    # Fail with arithmetic, not a deep device_put error: the batch axis is
    # laid over the data axes of the mesh, so their product must divide it.
    spec0 = batch_sharding.spec[0]
    names = spec0 if isinstance(spec0, tuple) else (spec0,)
    data_div = 1
    for name in names:
        if name is not None:
            data_div *= mesh.shape[name]
    if global_batch % data_div:
        raise ValueError(
            f"global_batch {global_batch} must be divisible by the mesh's"
            f" data-axis product {data_div} ({'x'.join(str(n) for n in names)})"
        )
    loader = ShardedLoader(dataset, global_batch, sharding=batch_sharding)

    start_step = 0
    resumed_from: Optional[int] = None
    if checkpoint_dir and (latest := ckpt.latest_step(checkpoint_dir)) is not None:
        restored = ckpt.restore(checkpoint_dir, tc, mesh, step=latest)
        state = restored["state"]
        start_step = int(restored["step"])
        resumed_from = start_step
        log.info("resumed from %s at step %d", checkpoint_dir, start_step)
    else:
        state = make_train_state(tc, jax.random.key(seed), mesh)
    loader.load_state_dict({"step": start_step})

    history: List[Dict[str, float]] = []
    step = start_step
    # A checkpoint already exists at the resume step — the trailing save
    # must not re-write it (orbax refuses to overwrite a finalized dir).
    last_saved = start_step if resumed_from is not None else -1
    t_mark = time.perf_counter()
    step_mark = step
    metrics = None
    batches = iter(loader)
    while step < total_steps:
        # Pull only when a step will actually run: the for-in shape would
        # pack (and with prefetch, device_put) one batch past the end.
        batch = next(batches)
        state, metrics = step_fn(state, batch)
        step += 1
        if log_every and (step % log_every == 0 or step == total_steps):
            # The only host sync point: pull the latest metrics once.
            m = jax.device_get(metrics)
            now = time.perf_counter()
            rec = {
                "step": float(step),
                "loss": float(m["loss"]),
                "grad_norm": float(m["grad_norm"]),
                "steps_per_s": (step - step_mark) / max(now - t_mark, 1e-9),
            }
            history.append(rec)
            log.info(
                "step %d loss %.4f grad_norm %.3f %.2f steps/s",
                step, rec["loss"], rec["grad_norm"], rec["steps_per_s"],
            )
            t_mark, step_mark = now, step
        if checkpoint_every and step % checkpoint_every == 0:
            ckpt.save(checkpoint_dir, state, step=step)
            last_saved = step
    if checkpoint_every and step > last_saved and step > 0:
        ckpt.save(checkpoint_dir, state, step=step)
    if metrics is not None and not history:
        m = jax.device_get(metrics)
        history.append({
            "step": float(step),
            "loss": float(m["loss"]),
            "grad_norm": float(m["grad_norm"]),
            "steps_per_s": 0.0,
        })
    return FitResult(
        state=state, step=step, history=history, resumed_from=resumed_from
    )
